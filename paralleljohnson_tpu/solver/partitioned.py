"""Condense-solve-expand partitioned APSP (ROADMAP item 3, second half;
PAPERS.md arXiv:2601.19907 "RAPID-Graph: Recursive All-Pairs Shortest
Paths" — the blocked Floyd-Warshall as the *combine* stage of a
partitioned solver).

Large sparse graphs have paid for APSP as B independent gather-bound
relaxation sweeps. This route buys them a dense MXU core instead:

  1. **Partition** the vertices around k seeded pivots (the same
     deterministic draw ``serve.landmarks`` uses), assigning each vertex
     to its hop-nearest pivot over the undirected structure (partition
     quality only moves work between stages — correctness never depends
     on it; stranded vertices are assigned round-robin).
  2. **Close each part locally**: blocked FW (``ops.fw``) on the part's
     dense submatrix — exact all-pairs distances USING ONLY that part's
     vertices.
  3. **Condense**: boundary vertices (endpoints of cross-part edges)
     form the core. Core seed entries = each part's local
     boundary-to-boundary closures, min'd with the raw cross edges.
     Blocked FW on the dense core then yields EXACT boundary-to-boundary
     distances in the full graph.
  4. **Expand**, one batched min-plus fan-out per partition: for sources
     S in part P, ``s2core = local_P[S, dP] (x) core[dP, :]`` gives the
     exact distance from every source to every core vertex, and the rows
     for targets in part Q are ``min(local_P[S, Q] if Q == P,
     s2core[:, dQ] (x) local_Q[dQ, Q])``.

**Why this is exact, not an approximation**: any shortest path
decomposes into maximal within-part runs joined by cross edges. Each
run's endpoints are boundary vertices (or the path's own endpoints),
and each run stays inside one part — so step 2 prices every run, step 3
prices every boundary-to-boundary middle section (its FW considers all
alternations of local runs and cross edges), and step 4's two min-plus
hops enumerate every (first exit, last entry) pair. Distances are
bitwise-reproducible against a plain solve whenever the weight set is
exactly representable (integer weights in tests); with general f32
weights the route agrees to ULP-level reassociation like any two dense
kernels.

Negative edges need no Johnson phases here (FW is sign-agnostic), and
negative-cycle detection is complete: a cycle inside one part turns a
local closure's diagonal negative; a cycle crossing parts turns the
core closure's diagonal negative.

Work accounting: exact tropical MACs, host ints — the sum of each
closure's ``fw_mac_count`` plus the expansion products' padded MAC
counts (``relax.minplus_padded_k``), on the same scale as every dense
counter.
"""

from __future__ import annotations

import math

import numpy as np

from paralleljohnson_tpu.graphs import CSRGraph
from paralleljohnson_tpu.ops import relax

ROUTE_TAG = "condensed+fw"

# Expansion min-plus k-blocking (relax.minplus): bounds the broadcast
# intermediate of the per-part products.
_EXPAND_KBLOCK = 128


def auto_num_parts(v: int) -> int:
    """Default partition count: ~sqrt(V)/8 clamped to [2, 32] — parts of
    ~8.sqrt(V) vertices keep the local dense closures comfortably under
    the core's cost while the boundary core stays dense enough to be an
    MXU workload. Any value is correct; this only shapes the work
    split."""
    return max(2, min(32, int(math.isqrt(max(v, 4))) // 8 or 2))


def partition_by_pivots(
    graph: CSRGraph, num_parts: int, *, seed: int = 0
) -> np.ndarray:
    """int64[V] part label per vertex: k pivots drawn with the
    ``serve.landmarks`` seeded-uniform idiom, then hop-layered BFS over
    the UNDIRECTED structure (direction matters for distances, not for
    "which part should own this vertex"). Ties break to the smallest
    pivot label (deterministic). Vertices unreachable from every pivot
    are assigned round-robin — correctness is label-independent."""
    v = graph.num_nodes
    k = max(1, min(int(num_parts), max(v, 1)))
    rng = np.random.default_rng(seed)
    pivots = np.sort(rng.choice(v, size=k, replace=False))
    labels = np.full(v, -1, np.int64)
    labels[pivots] = np.arange(k)
    e = graph.num_real_edges
    # Both directions once: the frontier relaxes over undirected hops.
    us = np.concatenate([graph.src[:e], graph.indices[:e]])
    vs = np.concatenate([graph.indices[:e], graph.src[:e]])
    while True:
        cand = np.full(v, np.iinfo(np.int64).max, np.int64)
        live = labels[us] >= 0
        np.minimum.at(cand, vs[live], labels[us[live]])
        fresh = (labels < 0) & (cand < np.iinfo(np.int64).max)
        if not fresh.any():
            break
        labels[fresh] = cand[fresh]
    left = np.flatnonzero(labels < 0)
    if left.size:
        labels[left] = np.arange(left.size) % k
    return labels


def _fw_closed(a_np: np.ndarray, tile_cfg: int):
    """Blocked-FW closure of one dense block (host in, host out).
    Returns (closed float32/64 [n, n], negative_cycle bool, macs int,
    k_steps int). Zero-sized blocks short-circuit."""
    import jax.numpy as jnp

    from paralleljohnson_tpu.ops import fw

    n = a_np.shape[0]
    if n == 0:
        return a_np, False, 0, 0
    tile = fw.effective_tile(n, tile_cfg)
    vp = fw.pad_tiles(n, tile)
    closed, neg = fw.fw_closure(
        fw.pad_dense(jnp.asarray(a_np), tile), tile=tile
    )
    return (
        np.asarray(closed[:n, :n]),
        bool(neg),
        fw.fw_mac_count(vp, tile),
        vp // tile,
    )


def _mp_jit():
    import functools

    import jax

    fn = getattr(_mp_jit, "_fn", None)
    if fn is None:
        fn = jax.jit(
            functools.partial(relax.minplus, k_block=_EXPAND_KBLOCK)
        )
        _mp_jit._fn = fn
    return fn


def _pad128(n: int) -> int:
    return 128 * max(1, -(-n // 128))


def _mp(d, a):
    """One expansion min-plus product ([B, K] (x) [K, N]) on device
    (jitted relax.minplus, k-blocked broadcast), materialized host-side
    — expansion blocks are assembled into the [B, V] numpy result. All
    three dims are padded to 128 multiples with +inf no-ops before the
    jitted call, so arbitrary part sizes share a handful of compiled
    shape buckets instead of recompiling per (part, part) pair."""
    import jax.numpy as jnp

    b, k = d.shape
    n = a.shape[1]
    bp, kp, np_ = _pad128(b), _pad128(k), _pad128(n)
    dp = np.full((bp, kp), np.inf, d.dtype)
    dp[:b, :k] = d
    ap = np.full((kp, np_), np.inf, a.dtype)
    ap[:k, :n] = a
    out = _mp_jit()(jnp.asarray(dp), jnp.asarray(ap))
    return np.asarray(out[:b, :n])


def _mp_macs(b: int, k: int, n: int) -> int:
    """Exact candidate ops of one padded expansion product — all three
    dims ride the 128 bucketing of :func:`_mp`, and the pad no-ops are
    performed, so they are counted (the dense accounting convention)."""
    return _pad128(b) * _pad128(k) * _pad128(n)


def _dense_block(graph, verts, lid, part_mask_src, src, dst, w):
    """Dense [n, n] submatrix of ``verts`` (0 diagonal, +inf non-edges,
    parallel edges resolved to the min) from the within-part edges."""
    n = verts.size
    a = np.full((n, n), np.inf, dtype=graph.dtype)
    np.fill_diagonal(a, 0.0)
    sel = np.flatnonzero(part_mask_src)
    if sel.size:
        np.minimum.at(a, (lid[src[sel]], lid[dst[sel]]), w[sel])
    return a


def solve_condensed(
    graph: CSRGraph,
    sources: np.ndarray | None = None,
    *,
    config=None,
    predecessors: bool = False,
    num_parts: int | None = None,
    seed: int = 0,
):
    """Exact partitioned APSP (see module docstring).

    Returns ``(dist [B, V] float, pred [B, V] int32 or None, info)`` —
    ``info`` carries route tag, exact MAC totals, k-step count, part and
    core sizes, and ``pred_ok`` (None when predecessors were not
    requested; False when the tight-edge tree check rejected the
    one-pass extraction — the caller falls back to the standard route).
    Raises ``NegativeCycleError`` on any reachable negative cycle.
    """
    from paralleljohnson_tpu.solver.johnson import NegativeCycleError

    v = graph.num_nodes
    sources = (
        np.arange(v, dtype=np.int64)
        if sources is None
        else np.asarray(sources, np.int64)
    )
    # Two of ISSUE 14's auto-tuned free parameters: an explicit config
    # value wins, else the profile-tuned value for this (platform,
    # shape bucket), else the hand-tuned constants (512 tile;
    # ~sqrt(V)/8 parts) — observe.tuning.
    from paralleljohnson_tpu import observe
    from paralleljohnson_tpu.observe.tuning import (
        DEFAULT_FW_TILE,
        resolve_param,
    )

    _platform = observe.current_platform()
    tile_cfg, _ = resolve_param(
        "fw_tile", getattr(config, "fw_tile", None), DEFAULT_FW_TILE,
        config=config, platform=_platform,
        num_nodes=v, num_edges=graph.num_real_edges,
        validate=lambda t_: isinstance(t_, int) and t_ >= 128
        and t_ % 128 == 0,
    )
    tile_cfg = int(tile_cfg)
    k, parts_source = resolve_param(
        "partition_parts",
        num_parts or getattr(config, "partition_parts", None),
        auto_num_parts(v),
        config=config, platform=_platform,
        num_nodes=v, num_edges=graph.num_real_edges,
        validate=lambda n_: isinstance(n_, int) and n_ >= 1,
    )
    k = int(k)

    labels = partition_by_pivots(graph, k, seed=seed)
    part_ids = np.unique(labels)
    parts = [np.flatnonzero(labels == p) for p in part_ids]

    e = graph.num_real_edges
    src, dst, w = graph.src[:e], graph.indices[:e], graph.weights[:e]
    cross = labels[src] != labels[dst]
    boundary_mask = np.zeros(v, bool)
    boundary_mask[src[cross]] = True
    boundary_mask[dst[cross]] = True
    boundary = np.flatnonzero(boundary_mask)
    core_idx = np.full(v, -1, np.int64)
    core_idx[boundary] = np.arange(boundary.size)
    nc = boundary.size

    macs = 0
    k_steps = 0
    lids = np.full(v, -1, np.int64)  # local id within own part
    locals_closed: list[np.ndarray] = []
    blocal: list[np.ndarray] = []  # per part: local ids of boundary verts
    bcore: list[np.ndarray] = []   # per part: core ids of those verts
    for p, verts in zip(part_ids, parts):
        lids[verts] = np.arange(verts.size)
        closed, neg, m, ks = _fw_closed(
            _dense_block(
                graph, verts, lids,
                (labels[src] == p) & ~cross, src, dst, w,
            ),
            tile_cfg,
        )
        if neg:
            raise NegativeCycleError(
                "negative-weight cycle inside a partition (condensed route)"
            )
        macs += m
        k_steps += ks
        locals_closed.append(closed)
        bv = verts[boundary_mask[verts]]
        blocal.append(lids[bv])
        bcore.append(core_idx[bv])

    # Condensed dense core: each part's local boundary-to-boundary
    # closure min'd with the raw cross edges, then closed with FW —
    # exact boundary-to-boundary distances in the FULL graph.
    core = np.full((nc, nc), np.inf, dtype=graph.dtype)
    if nc:
        np.fill_diagonal(core, 0.0)
        for closed, bl, bc in zip(locals_closed, blocal, bcore):
            if bl.size:
                core[np.ix_(bc, bc)] = np.minimum(
                    core[np.ix_(bc, bc)], closed[np.ix_(bl, bl)]
                )
        np.minimum.at(
            core, (core_idx[src[cross]], core_idx[dst[cross]]), w[cross]
        )
    core_closed, neg, m, ks = _fw_closed(core, tile_cfg)
    if neg:
        raise NegativeCycleError(
            "negative-weight cycle across partitions (condensed route)"
        )
    macs += m
    k_steps += ks

    # Expansion: one batched min-plus fan-out per source partition.
    # Dirty-window frontier schedule for the sparse phase (ISSUE 13;
    # the dense FW tiles above are untouched): a (source part P ->
    # target part Q) product can only contribute when some source
    # reaches Q's boundary through the core — when the s2core slice for
    # Q is entirely +inf the product is a min with +inf (the identity)
    # and is skipped EXACTLY, not heuristically. Counted per skip so
    # the work accounting stays honest. ``config.dirty_window=False``
    # disables the gate (the pre-ISSUE schedule).
    dw_gate = getattr(config, "dirty_window", "auto") is not False
    expand_skipped = 0
    macs_skipped = 0
    dist = np.full((sources.size, v), np.inf, dtype=graph.dtype)
    src_rows_seen: dict[int, list[int]] = {}
    for i, s in enumerate(sources):
        src_rows_seen.setdefault(int(s), []).append(i)
    for pi, (p, verts) in enumerate(zip(part_ids, parts)):
        rows = [r for s in verts for r in src_rows_seen.get(int(s), [])]
        if not rows:
            continue
        rows = np.asarray(rows, np.int64)
        ls = lids[sources[rows]]
        local_p = locals_closed[pi]
        dist[np.ix_(rows, verts)] = local_p[ls]
        if nc == 0 or blocal[pi].size == 0:
            continue  # no way out of this part: local rows are final
        # d(s, c) for EVERY core vertex c: local to own boundary, then
        # through the closed core. MACs counted on the padded scale.
        s2core = _mp(local_p[np.ix_(ls, blocal[pi])], core_closed[bcore[pi]])
        macs += _mp_macs(rows.size, blocal[pi].size, nc)
        for qi, (q, verts_q) in enumerate(zip(part_ids, parts)):
            if blocal[qi].size == 0:
                continue  # no way into q from outside
            entry = s2core[:, bcore[qi]]
            if dw_gate and not np.isfinite(entry).any():
                # No source of this batch reaches Q's boundary: the
                # whole [rows, Q] product is +inf and cannot lower
                # anything. Exact skip (disconnected / unreachable
                # part pairs never pay dense expansion work).
                expand_skipped += 1
                macs_skipped += _mp_macs(
                    rows.size, blocal[qi].size, verts_q.size
                )
                continue
            upd = _mp(entry, locals_closed[qi][blocal[qi]])
            macs += _mp_macs(rows.size, blocal[qi].size, verts_q.size)
            dist[np.ix_(rows, verts_q)] = np.minimum(
                dist[np.ix_(rows, verts_q)], upd
            )

    route = ROUTE_TAG
    pred = None
    pred_ok = None
    if predecessors:
        pred, pred_ok = _extract_pred(graph, dist, sources)
        if pred_ok:
            route = ROUTE_TAG + "+pred"
        else:
            pred = None

    info = {
        "route": route,
        "macs": int(macs),
        "k_steps": int(k_steps),
        "num_parts": len(parts),
        "core_size": int(nc),
        "part_sizes": [int(p.size) for p in parts],
        "pred_ok": pred_ok,
        # Dirty-window expansion gating (exact counters): part-pair
        # products proven all-inf and skipped, and the padded MACs they
        # would have cost.
        "expand_products_skipped": int(expand_skipped),
        "expand_macs_skipped": int(macs_skipped),
        # The resolved auto-tuned parameters + provenance (ISSUE 14):
        # ride the solver's plan record so the tuner can compare
        # alternatives per (platform, shape bucket).
        "params": {"fw_tile": tile_cfg, "partition_parts": int(k)},
        "params_source": {"partition_parts": parts_source},
    }
    return dist, pred, info


def _extract_pred(graph: CSRGraph, dist: np.ndarray, sources: np.ndarray):
    """One tight-edge extraction pass (ops.pred) over the converged
    expanded distances — the condensed route dispatches predecessors
    exactly like every other route: same pass, same pointer-doubling
    tree certificate, same fallback signal (ok=False) on the zero-weight
    tight cycles no single-pass rule can resolve."""
    import jax.numpy as jnp

    from paralleljohnson_tpu.ops.pred import extract_pred

    e = graph.num_real_edges
    pred, ok = extract_pred(
        jnp.asarray(dist),
        jnp.asarray(sources, jnp.int32),
        jnp.asarray(graph.src[:e], jnp.int32),
        jnp.asarray(graph.indices[:e], jnp.int32),
        jnp.asarray(graph.weights[:e]),
    )
    return np.asarray(pred), bool(ok)
