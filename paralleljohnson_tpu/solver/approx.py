"""The certified (1+ε) approximate APSP route ``hopset+bf`` (ISSUE 17,
ROADMAP item 5).

``approx_apsp`` answers source batches with β-hop-bounded Bellman-Ford
over ``G ∪ H`` (H = ``ops.hopset`` shortcut edges) and attaches a
CERTIFIED per-entry bound to every row: the estimate is always a real
path length (or +inf), and ``max_error`` is the width of the tightest
interval provable from (a) the query sweep's own fixpoint flag — a
converged sweep over ``G ∪ H`` IS exact, error 0; (b) the hopset pivot
rows' triangle-inequality interval; (c) the landmark index's directed
interval when the caller holds one. Tighter wins per entry, the repo's
composition rule — and an unreachable pair is reported unreachable
(``inf``), never silently bounded: a pair no certificate pins reports
``(inf, inf)``.

``solve_with_budget`` is the arbitration point: a ``planner.Plan`` pair
(``hopset+bf`` vs ``exact``) walked through the ordinary ``select()``
machinery. The hopset plan qualifies ONLY under a positive
``error_budget`` on a negative-free graph (zero budget always solves
exactly — asserted in tests), the CostModel prices the two against
``kind:"plan"``/analytic records when a profile store is fitted, and
the build walk degrades exactly like the backend dispatch sites: a
failed hopset build falls through to the exact plan, never crashes the
solve.

Fleet sharding (``fleet_build_hopset``): construction fans out over
pivot ranges through the round-15 coordinator — each lease builds its
range's forward/reverse bounded-hop rows and commits them as a
digest-guarded npz artifact in its shard dir; the merge is a
start-ordered concatenation. Bitwise determinism vs a single-worker
build is a THEOREM of the sweep kernel (each row depends only on its
own source and the sweep count past its fixpoint is a no-op — see
``ops.hopset.bounded_hop_rows``), and the fleet test asserts it.
"""

from __future__ import annotations

import dataclasses
import time
import types
from pathlib import Path

import numpy as np

from paralleljohnson_tpu import planner as _planner
from paralleljohnson_tpu.config import SolverConfig
from paralleljohnson_tpu.ops import hopset as hs
from paralleljohnson_tpu.utils.checkpoint import graph_digest
from paralleljohnson_tpu.utils.telemetry import NULL_TELEMETRY

# The same f32-slack widening the landmark bounds use: the query rows
# are f32 path sums, so the served estimate is nudged up by this
# relative tolerance before the interval is finished — ``lower <=
# exact <= estimate`` must be a contract, not a rounding coin flip.
_F32_TOL = 32 * float(np.finfo(np.float32).eps)


@dataclasses.dataclass
class ApproxResult:
    """A ``hopset+bf`` solve: estimates + per-entry certified bounds.

    ``dist[i, v]`` estimates d(sources[i], v); ``max_error[i, v]``
    certifies ``|dist - exact| <= max_error`` (f32-rounding slack where
    the answer is proven exact by a converged sweep, exactly 0 for a
    proven-unreachable pair; +inf where no certificate pins the pair,
    which is also the only case an estimate of +inf is NOT a proven
    unreachability)."""

    dist: np.ndarray
    sources: np.ndarray
    max_error: np.ndarray
    hopset: hs.Hopset
    route: str = "hopset+bf"
    converged: bool = False
    stats: dict = dataclasses.field(default_factory=dict)
    plan: dict | None = None

    @property
    def exact(self) -> bool:
        """True when every entry is certified exact to f32 rounding
        (the sweep over ``G ∪ H`` reached its fixpoint in every
        batch)."""
        return bool(self.converged)

    @property
    def matrix(self) -> np.ndarray:
        order = np.argsort(self.sources)
        return np.asarray(self.dist)[order]


def _widen_up(rows: np.ndarray) -> np.ndarray:
    finite = np.isfinite(rows)
    return np.where(finite, rows + _F32_TOL * (1.0 + np.abs(rows)), rows)


def _compose_bounds(u_row, hop_lower, hop_upper, landmarks, s):
    """One row's certified interval: the query upper ``u_row`` (widened
    for f32 slack) min'd with the hopset interval's upper and, when a
    landmark index is attached, the landmark interval — lower is the
    max of the lowers, upper the min of the uppers (tighter wins; both
    sides stay certified because each input interval is)."""
    from paralleljohnson_tpu.serve import landmarks as lm

    lower, upper = hop_lower, np.minimum(hop_upper, _widen_up(u_row))
    if landmarks is not None and landmarks.k > 0:
        lm_lower, lm_upper = landmarks.bounds_row(s)
        lower = np.maximum(lower, lm_lower)
        upper = np.minimum(upper, lm_upper)
    return lm.finish_estimates(lower, upper)


def hopset_record(hopset: hs.Hopset, graph, *, platform: str) -> dict:
    """The ``kind: "hopset"`` profile-store record — what
    ``observe.regress`` buckets by shape so a hopset that got slower or
    fatter flags like any other regression (ISSUE 17 satellite)."""
    return {
        "ts": time.time(),
        "kind": "hopset",
        "platform": platform,
        "nodes": int(graph.num_nodes),
        "edges": int(graph.num_real_edges),
        "epsilon": float(hopset.epsilon),
        "beta": int(hopset.beta),
        "k": int(hopset.k),
        "hopset_edges": int(hopset.num_hopset_edges),
        "converged": bool(hopset.converged),
        "picker": hopset.picker,
        "construction_s": float(hopset.construction_s),
        "edges_examined": int(hopset.edges_examined),
    }


def _platform() -> str:
    try:
        import jax

        return jax.default_backend()
    except Exception:  # noqa: BLE001 — record-keeping must not require a device
        return "unknown"


def _profile_store(config):
    import os

    root = config.profile_store or os.environ.get("PJ_PROFILE_DIR")
    if not root:
        return None
    from paralleljohnson_tpu.observe.store import ProfileStore

    return ProfileStore(root)


def _planner_model(config):
    if config.planner is False:
        return None
    store = _profile_store(config)
    if store is None:
        return None
    try:
        from paralleljohnson_tpu.observe.store import CostModel

        return CostModel.fit(store)
    except Exception:  # noqa: BLE001 — an unreadable store means unpriced, not broken
        return None


def approx_apsp(
    graph,
    sources: np.ndarray | None = None,
    *,
    config: SolverConfig | None = None,
    epsilon: float | None = None,
    hopset: hs.Hopset | None = None,
    landmarks=None,
    telemetry=None,
) -> ApproxResult:
    """Certified approximate APSP over ``graph`` (see module docstring).

    ``hopset=None`` builds one (ε/β/k from the config's approx knobs);
    a prebuilt/persisted hopset is digest-checked against the graph.
    ``landmarks`` optionally composes the landmark index's interval
    into every bound (tighter wins per entry). Requires a negative-free
    graph — the hopset certificates assume ``d >= 0`` and the Johnson
    reweighting phases are exact-route machinery."""
    config = config or SolverConfig()
    tel = telemetry if telemetry is not None else (
        config.telemetry if config.telemetry is not None else NULL_TELEMETRY
    )
    if graph.has_negative_weights:
        raise ValueError(
            "hopset+bf requires non-negative weights (the certificates "
            "clamp lower bounds at 0); use the exact routes for "
            "negative-edge graphs"
        )
    epsilon = float(
        config.approx_epsilon if epsilon is None else epsilon
    )
    v = graph.num_nodes
    sources = (
        np.arange(v, dtype=np.int64) if sources is None
        else np.asarray(sources, np.int64)
    )
    digest = graph_digest(graph)
    stats: dict = {"epsilon": epsilon}
    if hopset is None:
        hopset = hs.build_hopset(
            graph, epsilon=epsilon, beta=config.approx_beta,
            telemetry=tel,
        )
        store = _profile_store(config)
        if store is not None:
            store.append(
                hopset_record(hopset, graph, platform=_platform())
            )
    elif hopset.digest is not None and hopset.digest != digest:
        raise ValueError(
            "hopset was built for a different graph (digest mismatch) — "
            "rebuild it; a wrong-graph shortcut set cannot certify"
        )
    stats.update(
        beta=int(hopset.beta), k=int(hopset.k),
        hopset_edges=int(hopset.num_hopset_edges),
        construction_s=float(hopset.construction_s),
        hopset_converged=bool(hopset.converged),
    )

    t0 = time.perf_counter()
    batch = config.source_batch_size or max(16, min(1024, v))
    dist = np.empty((len(sources), v), np.float64)
    max_error = np.empty((len(sources), v), np.float64)
    all_converged = True
    examined = 0
    with tel.span("approx_query", op="hopset+bf", n_sources=len(sources),
                  beta=int(hopset.beta)):
        for lo in range(0, len(sources), batch):
            batch_sources = sources[lo:lo + batch]
            # The union sweep, reorganized: the star edges can only
            # ever contribute through the 2-hop relay s -> p -> v, so
            # the relay rows are hoisted into the seed and the β-hop
            # sweep runs over G alone — E edges per round, not
            # E + 2·k·V (see Hopset.relay_rows for the bit-match
            # argument; the seeded fixpoint stays EXACT).
            seed = (
                hopset.relay_rows(batch_sources)
                if hopset.k > 0 else None
            )
            rows, _, conv, ex = hs.bounded_hop_rows(
                graph, batch_sources, beta=hopset.beta, seed_rows=seed,
            )
            examined += ex
            rows = np.asarray(rows, np.float64)
            if conv:
                # Fixpoint over G ∪ H == fixpoint over G (hopset edges
                # are realizable): these rows are exact up to f32
                # rounding, +inf included. The certificate carries the
                # rounding slack — two f32 routes summing the same path
                # in different orders disagree at ulp level, and the
                # bound must hold against ANY exact route's output.
                dist[lo:lo + len(batch_sources)] = rows
                max_error[lo:lo + len(batch_sources)] = np.where(
                    np.isfinite(rows),
                    _F32_TOL * (1.0 + np.abs(rows)), 0.0,
                )
                continue
            all_converged = False
            for i, s in enumerate(batch_sources):
                hop_lower, hop_upper = hopset.bounds_row(int(s))
                est, err = _compose_bounds(
                    rows[i], hop_lower, hop_upper, landmarks, int(s)
                )
                dist[lo + i] = est
                max_error[lo + i] = err
    query_s = time.perf_counter() - t0
    stats.update(
        query_s=query_s, edges_examined=int(examined),
        query_converged=bool(all_converged),
        batches=-(-len(sources) // batch),
    )
    return ApproxResult(
        dist=dist, sources=sources, max_error=max_error, hopset=hopset,
        converged=all_converged, stats=stats,
    )


# -- budget arbitration: the planner picks exact vs approximate --------------


def _qual_hopset(ctx) -> tuple[bool, str]:
    if ctx.config.hopset is False:
        return False, "hopset disabled by config"
    if not ctx.error_budget > 0:
        return False, "error budget is 0 — exact is the only honest answer"
    if ctx.graph.has_negative_weights:
        return False, "negative weights: hopset certificates need d >= 0"
    return True, (
        f"budget {ctx.error_budget:g} admits a certified "
        f"(1+{ctx.config.approx_epsilon:g}) tier"
    )


def _contract_hopset(ctx) -> None:
    if ctx.config.hopset is True and not ctx.error_budget > 0:
        raise ValueError(
            "hopset=True forces the approximate plan but error_budget is "
            "0 — forcing an unflagged approximation is a contract "
            "violation; set a positive budget or drop the force"
        )


def _build_hopset_plan(ctx):
    return approx_apsp(
        ctx.graph, ctx.sources, config=ctx.config, hopset=ctx.hopset,
        landmarks=ctx.landmarks, telemetry=ctx.telemetry,
    )


def _fail_hopset(_owner, ctx) -> None:
    # Called INSIDE the ranking walk's active except: a bare raise
    # propagates the original failure. A forced approximate plan must
    # fail loud (the backend dispatch convention); an auto one degrades
    # to the exact plan below it.
    if ctx.config.hopset is True:
        raise


def _build_exact_plan(ctx):
    from paralleljohnson_tpu.solver import ParallelJohnsonSolver

    return ParallelJohnsonSolver(ctx.config).solve(ctx.graph, ctx.sources)


APPROX_PLANS = [
    _planner.Plan(
        name="hopset+bf", entry="apsp", priority=10,
        qualify=_qual_hopset, contract=_contract_hopset,
        build=_build_hopset_plan, failure=_fail_hopset,
        price_routes=("hopset+bf",),
        forced=lambda cfg: getattr(cfg, "hopset", "auto") is True,
        force_overrides={"hopset": True},
        tunables=("approx_beta",),
    ),
    _planner.Plan(
        name="exact", entry="apsp", priority=20,
        qualify=lambda ctx: (True, "exact solve always qualifies"),
        build=_build_exact_plan,
        price_routes=("vm-blocked", "vm", "gs", "sweep"),
        forced=lambda cfg: getattr(cfg, "hopset", "auto") is False,
        force_overrides={"hopset": False},
    ),
]


def solve_with_budget(
    graph,
    sources: np.ndarray | None = None,
    *,
    config: SolverConfig | None = None,
    error_budget: float | None = None,
    hopset: hs.Hopset | None = None,
    landmarks=None,
    telemetry=None,
):
    """Solve ``graph`` under a relative error budget: the planner walks
    ``APPROX_PLANS`` (priced by the CostModel when a store is fitted;
    declared priority otherwise — the approximate tier leads exactly
    when a positive budget qualifies it, and a zero budget pins exact).
    Returns ``(result, decision)`` — result is an :class:`ApproxResult`
    or an exact ``SolveResult``; both carry ``.plan`` (the decision
    record, also appended to the profile store as ``kind:"plan"``)."""
    config = config or SolverConfig()
    budget = float(
        config.error_budget if error_budget is None else error_budget
    )
    if budget < 0:
        raise ValueError(f"error_budget must be >= 0, got {budget!r}")
    ctx = types.SimpleNamespace(
        graph=graph, sources=sources, config=config,
        error_budget=budget, hopset=hopset, landmarks=landmarks,
        telemetry=telemetry, params={},
    )
    t0 = time.perf_counter()
    decision = _planner.select(
        APPROX_PLANS, ctx, model=_planner_model(config),
        platform=_platform(), num_edges=graph.num_real_edges,
        batch=graph.num_nodes if sources is None else len(sources),
        config=config,
    )
    result = None
    for cand in decision.ranking:
        try:
            result = cand.plan.build(ctx)
        except Exception:
            if cand.plan.failure is None:
                raise
            cand.plan.failure(None, ctx)
            continue
        if result is None:
            continue
        decision.params.update(ctx.params)
        result.plan = decision.as_dict(built=cand.plan.name)
        break
    if result is None:
        raise RuntimeError(
            "planner: every qualified apsp plan failed "
            f"(ranking: {[c.plan.name for c in decision.ranking]})"
        )
    store = _profile_store(config)
    if store is not None:
        store.append(_planner.plan_record(
            result.plan, label="solve_with_budget",
            platform=_platform(), num_nodes=graph.num_nodes,
            num_edges=graph.num_real_edges,
            batch=graph.num_nodes if sources is None else len(sources),
            wall_s=time.perf_counter() - t0,
        ))
    return result, decision


# -- fleet-sharded construction ---------------------------------------------

_SHARD_PREFIX = "hopset_"


def _shard_path(shard_dir: Path, start: int, stop: int) -> Path:
    return shard_dir / f"{_SHARD_PREFIX}{start:08d}_{stop:08d}.npz"


def build_hopset_shard(
    coord, worker: str, lease, graph, pivots: np.ndarray, *,
    beta: int, digest: str, reverse_graph=None, telemetry=None,
) -> Path:
    """One lease's unit of hopset construction: bounded-hop rows for
    ``pivots[lease.start:lease.stop]``, committed as an ordinary
    digest-guarded npz artifact in the worker's shard dir (tmp +
    rename, the checkpoint discipline)."""
    sub = pivots[lease.start:lease.stop]
    fwd, rev, converged, examined = hs.build_pivot_rows(
        graph, sub, beta=beta, reverse_graph=reverse_graph,
        telemetry=telemetry,
    )
    shard_dir = coord.shard_dir(worker)
    shard_dir.mkdir(parents=True, exist_ok=True)
    path = _shard_path(shard_dir, lease.start, lease.stop)
    tmp = path.with_suffix(".tmp.npz")
    np.savez_compressed(
        tmp, start=np.array(lease.start), stop=np.array(lease.stop),
        pivots=np.asarray(sub, np.int64), fwd=fwd, rev=rev,
        converged=np.array(bool(converged)),
        examined=np.array(int(examined), np.int64),
        digest=np.array(digest), beta=np.array(int(beta)),
    )
    tmp.rename(path)
    return path


def merge_hopset_shards(
    directory: str | Path, graph, *, epsilon: float, seed: int = 0,
    picker: str = "uniform", expect_k: int | None = None,
) -> hs.Hopset:
    """Union the committed shard artifacts under ``directory`` (a
    coordinator dir) into one :class:`~ops.hopset.Hopset` — shards are
    ordered by their pivot-range start, digest-checked against
    ``graph``, and must tile ``[0, k)`` exactly (a gap means an
    uncommitted lease: fail loud, never serve a partial hopset)."""
    directory = Path(directory)
    digest = graph_digest(graph)
    shards = []
    for path in sorted(directory.glob(f"shards/*/{_SHARD_PREFIX}*.npz")):
        with np.load(path) as data:
            if str(data["digest"]) != digest:
                raise ValueError(
                    f"{path}: hopset shard built for a different graph "
                    "(digest mismatch)"
                )
            shards.append((
                int(data["start"]), int(data["stop"]),
                data["pivots"], data["fwd"], data["rev"],
                bool(data["converged"]), int(data["examined"]),
                int(data["beta"]),
            ))
    shards.sort(key=lambda s: s[0])
    expected = 0
    for start, stop, *_ in shards:
        if start != expected:
            raise ValueError(
                f"hopset shards do not tile the pivot range: expected "
                f"start {expected}, found {start}"
            )
        expected = stop
    if expect_k is not None and expected != expect_k:
        raise ValueError(
            f"hopset shards cover {expected} of {expect_k} pivots — "
            "uncommitted lease(s) outstanding"
        )
    if not shards:
        raise ValueError(f"{directory}: no committed hopset shards")
    beta = shards[0][7]
    return hs.Hopset(
        epsilon=float(epsilon), beta=beta,
        pivots=np.concatenate([s[2] for s in shards]),
        fwd=np.vstack([s[3] for s in shards]),
        rev=np.vstack([s[4] for s in shards]),
        converged=all(s[5] for s in shards),
        nonnegative=not graph.has_negative_weights,
        digest=digest, picker=picker, seed=int(seed),
        edges_examined=sum(s[6] for s in shards),
    )


def fleet_build_hopset(
    directory: str | Path, graph, *, n_workers: int = 2,
    epsilon: float = 0.1, k: int | None = None, beta: int | None = None,
    seed: int = 0, picker: str = "uniform", telemetry=None,
) -> hs.Hopset:
    """Fleet-sharded hopset construction, in-process (the round-15
    smoke idiom: same coordinator machinery — lease claims over the
    flock'd log, per-worker shard artifacts, commit records — minus
    subprocess spawn). The merged hopset is bitwise-identical to
    ``ops.hopset.build_hopset`` with the same (graph, ε, k, β, seed,
    picker) — asserted in the fleet tests."""
    from paralleljohnson_tpu.distributed.coordinator import Coordinator
    from paralleljohnson_tpu.serve.landmarks import pick_pivots

    t0 = time.perf_counter()
    v = graph.num_nodes
    k = hs.auto_num_pivots(v) if k is None else max(0, min(int(k), v))
    beta = hs.auto_beta(v, epsilon) if beta is None else int(beta)
    pivots = pick_pivots(graph, k, seed=seed, picker=picker)
    digest = graph_digest(graph)
    n_workers = max(1, int(n_workers))
    coord = Coordinator.create(
        directory, graph_spec=f"hopset:{digest[:12]}",
        graph_digest=digest, num_sources=len(pivots),
        lease_sources=max(1, -(-len(pivots) // n_workers)),
    )
    rg = graph.reverse()
    workers = [f"hopset-w{i}" for i in range(n_workers)]
    progress = True
    while progress:
        progress = False
        for w in workers:
            lease = coord.claim(w)
            if lease is None:
                continue
            build_hopset_shard(
                coord, w, lease, graph, pivots, beta=beta,
                digest=digest, reverse_graph=rg, telemetry=telemetry,
            )
            coord.commit(lease.lease_id, w)
            progress = True
    merged = merge_hopset_shards(
        coord.dir, graph, epsilon=epsilon, seed=seed, picker=picker,
        expect_k=len(pivots),
    )
    merged.construction_s = time.perf_counter() - t0
    return merged
