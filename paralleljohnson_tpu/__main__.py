"""``python -m paralleljohnson_tpu`` entry point."""

import sys

from paralleljohnson_tpu.cli import main

sys.exit(main())
