"""Priced planner registry — self-driving dispatch (ISSUE 14 tentpole,
ROADMAP item 2).

Route selection used to be a hand-ordered if/else ladder in
``backends/jax_backend.py`` that every new kernel family thickened.
This module is the registry that replaces it: each kernel family
declares a :class:`Plan` with

- a **contract** hook — the loud ``NotImplementedError`` checks a
  forced flag carries (e.g. ``fw=True`` on a multi-device mesh), run
  for every dispatch regardless of which plan ends up serving it, so
  "True forces" can never be silently routed around;
- a **qualification predicate** — the graph/mesh/config preconditions
  under which the plan may serve a solve (the same ``_use_*``
  predicates the ladder consulted, now data instead of branch order);
- a **cost hook** — the route tags the persisted
  :class:`~paralleljohnson_tpu.observe.store.CostModel` prices the
  plan by (trajectory-based refinement, e.g. the dirty-window
  ``dw_decision`` evidence gate, stays inside the plan's own
  qualification — pricing refines ordering, evidence gates entry);
- a **build function** — the kernel invocation itself, returning a
  ``KernelResult`` (or ``None`` when a required layout is unavailable,
  which hands the solve to the next plan in the ranking);
- a **failure policy** — what the ladder's ``except`` blocks did:
  warn-once + disable-for-this-backend-instance on an auto route,
  propagate on a forced one.

:func:`select` turns the registry into a decision: contracts first,
then qualification in declared priority order (the ladder order,
preserved bit-for-bit when nothing is priced), then — when the profile
store's calibration prices both the priority incumbent and a cheaper
challenger — a priced promotion. The promotion is deliberately
conservative:

- an **unpriced route must read as unpriced, not free**: a challenger
  is only promoted above an incumbent when BOTH carry predictions;
- a **forced flag pins its plan** (qualification override — the flag
  maps to "this plan first", not to a branch position);
- the challenger must beat the incumbent by more than
  :data:`PLANNER_NOISE_BAND` — the cost model is fitted from min-of-
  samples walls that still wobble run to run; re-routing inside the
  noise band would flap between bitwise-different (but equally
  correct) kernels per batch.

With an empty profile store every selection therefore reproduces the
pre-registry ladder exactly (the acceptance contract: distances stay
bitwise-identical to the old dispatch on every route).

Stdlib-only on purpose (the ``observe`` discipline): offline readers
and ``cli info`` consult the registry without importing jax.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable

# Priced-promotion noise band: a challenger must predict more than this
# fraction BELOW the incumbent to displace it. The calibration's
# per-unit seconds are min-of-samples (steady state) but the walls they
# were fitted from wobble 10-20% on shared hosts (the bench_regress
# DEFAULT_BAND rationale); 25% promotes real regime differences (the
# measured route gaps are 2-10x) without flapping inside timing noise.
PLANNER_NOISE_BAND = 0.25

# Every route tag the registry's plans can resolve to (plus the
# solver-level and repair families that share the priced table). The
# ``cli info`` priced-route table walks this list so a route with no
# profile samples appears with an explicit ``unpriced`` marker instead
# of being silently omitted.
KNOWN_ROUTES = (
    "sweep", "sweep-sm", "vm", "vm-blocked", "vm-blocked+dw",
    "pallas-vm", "gs", "gs+dw", "dia", "bucket", "bucket+sweep",
    "frontier", "fw", "fw-tile", "dense-squaring", "dense-iterate",
    "condensed+fw", "incremental-repair", "lookup-host", "lookup-device",
    "hopset+bf",
)


@dataclasses.dataclass(frozen=True)
class Plan:
    """One kernel family's dispatch declaration (see module docstring).

    ``entry`` scopes the plan to a dispatch site: ``"fanout"`` (the
    batched multi-source loop — the registry-driven site), ``"sssp"``
    (the B=1 Bellman-Ford families), ``"solver"`` (solver-level routes
    like the condensed partitioned solve). ``priority`` is the ladder
    position (lower = earlier); with no pricing the ranking IS this
    order. ``price_routes`` are tried in order against the CostModel —
    the first priced tag wins (a family whose route tag varies, e.g.
    ``fw``/``fw-tile``, lists both). ``force_overrides`` is the config
    patch that pins dispatch to this plan — what the bench harness uses
    to measure every qualified plan on one graph. ``tunables`` names
    the knobs (``observe.tuning.TUNABLE_PARAMS`` vocabulary) whose
    value shapes this plan's wall — what the self-proposing tuner
    (``tuner.py``, ISSUE 19) enumerates candidates for. ``price_batch``
    overrides the dispatch-level ``batch`` for THIS plan's pricing
    (e.g. an incremental repair is priced at its affected-row count
    while the full re-solve prices at B=V — one ``select()`` call, two
    honest work units)."""

    name: str
    entry: str
    priority: int
    qualify: Callable[[Any], tuple[bool, str]]
    build: Callable[[Any], Any] | None = None
    contract: Callable[[Any], None] | None = None
    price_routes: tuple[str, ...] = ()
    forced: Callable[[Any], bool] = lambda config: False
    failure: Callable[[Any, Any], None] | None = None
    force_overrides: dict = dataclasses.field(default_factory=dict)
    tunables: tuple[str, ...] = ()
    price_batch: Callable[[Any], int] | None = None


@dataclasses.dataclass
class PlanCandidate:
    """One plan's evaluation inside a :class:`PlanDecision`."""

    plan: Plan
    qualified: bool
    reason: str
    predicted_s: float | None = None
    priced_route: str | None = None
    forced: bool = False

    def as_dict(self) -> dict:
        out = {
            "plan": self.plan.name,
            "qualified": bool(self.qualified),
            "reason": self.reason,
        }
        if self.forced:
            out["forced"] = True
        if self.predicted_s is not None:
            out["predicted_s"] = float(self.predicted_s)
            out["priced_route"] = self.priced_route
        elif self.qualified:
            # The explicit marker: a candidate with no calibration is
            # UNPRICED, never silently omitted or treated as free.
            out["unpriced"] = True
        return out


@dataclasses.dataclass
class PlanDecision:
    """The outcome of one :func:`select` call: the chosen plan, the
    degrade-don't-crash ranking behind it, and the why-line."""

    chosen: PlanCandidate
    ranking: list[PlanCandidate]
    candidates: list[PlanCandidate]
    reason: str
    params: dict = dataclasses.field(default_factory=dict)

    def as_dict(self, *, built: str | None = None) -> dict:
        out = {
            "chosen": self.chosen.plan.name,
            "reason": self.reason,
            "candidates": [c.as_dict() for c in self.candidates],
        }
        if built is not None and built != self.chosen.plan.name:
            # The chosen plan's build degraded (layout unavailable /
            # auto-route failure) and a lower-ranked plan served the
            # solve — the decision record must say what actually ran.
            out["built"] = built
            out["degraded"] = True
        if self.params:
            out["params"] = dict(self.params)
        return out


def select(
    plans: list[Plan],
    ctx: Any,
    *,
    model=None,
    platform: str | None = None,
    num_edges: int | None = None,
    batch: int = 1,
    config=None,
    band: float = PLANNER_NOISE_BAND,
) -> PlanDecision:
    """Pick the cheapest qualified plan (see module docstring for the
    promotion rules). ``model`` is a fitted ``CostModel`` or None (no
    pricing — pure declared priority, i.e. the ladder). Contract hooks
    run FIRST, for every plan, in priority order: a forced-flag
    violation must raise before any route is built, exactly as the
    ladder's top-of-function checks did."""
    ordered = sorted(plans, key=lambda p: p.priority)
    for plan in ordered:
        if plan.contract is not None:
            plan.contract(ctx)
    candidates: list[PlanCandidate] = []
    for plan in ordered:
        ok, reason = plan.qualify(ctx)
        candidates.append(
            PlanCandidate(
                plan=plan,
                qualified=bool(ok),
                reason=reason,
                forced=bool(plan.forced(config)) if config is not None
                else False,
            )
        )
    qualified = [c for c in candidates if c.qualified]
    if not qualified:
        raise RuntimeError(
            "planner: no qualified plan for this dispatch (the registry "
            "must always include an unconditional fallback)"
        )
    if model is not None and num_edges:
        for cand in qualified:
            plan_batch = (
                int(cand.plan.price_batch(ctx))
                if cand.plan.price_batch is not None else batch
            )
            for route in cand.plan.price_routes:
                pred = model.predict(
                    route, num_edges=num_edges, batch=plan_batch,
                    platform=platform,
                )
                if pred is not None:
                    cand.predicted_s = float(pred["predicted_s"])
                    cand.priced_route = route
                    break
    forced = [c for c in qualified if c.forced]
    incumbent = qualified[0]
    chosen = incumbent
    if forced:
        chosen = forced[0]
        reason = (
            f"forced by config ({chosen.plan.name}): qualification "
            "override pins the plan regardless of price"
        )
    elif incumbent.predicted_s is not None:
        challengers = [
            c for c in qualified[1:]
            if c.predicted_s is not None
            and c.predicted_s < incumbent.predicted_s * (1.0 - band)
        ]
        if challengers:
            chosen = min(challengers, key=lambda c: c.predicted_s)
            reason = (
                f"priced: {chosen.plan.name} predicts "
                f"{chosen.predicted_s:.4g}s < incumbent "
                f"{incumbent.plan.name} {incumbent.predicted_s:.4g}s "
                f"(> {band:.0%} apart)"
            )
        else:
            reason = (
                f"priority: incumbent {incumbent.plan.name} "
                f"({incumbent.predicted_s:.4g}s predicted) has no "
                f"challenger beyond the {band:.0%} noise band"
            )
    else:
        reason = (
            f"priority: {incumbent.plan.name} is the first qualified "
            "plan and is unpriced (no calibration for this shape — "
            "priced promotion needs both routes priced)"
        )
    ranking = [chosen] + [c for c in qualified if c is not chosen]
    return PlanDecision(
        chosen=chosen, ranking=ranking, candidates=candidates,
        reason=reason,
    )


# -- the serving-tier lookup family (ISSUE 16) -------------------------------
#
# The query engine dispatches each aggregated batch's LOOKUP work (exact
# hot hits + landmark bounds) through this registry exactly like the
# backend dispatches a fan-out: ``device_lookup`` megabatches the batch
# into one kernel launch over the store's device tile, ``host_lookup``
# is the per-source tier walk the engine always had. Both produce
# bitwise-identical answers (the device path's design invariant — see
# ``serve/device_query.py``), so the choice is pure economics: tiny
# batches and CPU platforms keep the host path by qualification, a
# priced calibration or a forced ``device_lookup="on"``/``"off"`` pin
# overrides. The ``ctx`` is the engine's per-batch lookup context
# (``platform`` / ``device_available`` / ``device_reason`` /
# ``n_device_eligible`` / ``forced_on``); ``config`` carries the
# engine's ``device_lookup`` tristate.

# Below this many device-eligible lookups in a batch the kernel-launch
# overhead dwarfs the per-query saving — the host walk keeps them.
MIN_DEVICE_LOOKUP_BATCH = 4


def _qual_device_lookup(ctx):
    if not getattr(ctx, "device_available", False):
        return False, getattr(ctx, "device_reason",
                              "device query path unavailable")
    if getattr(ctx, "forced_on", False):
        return True, "device megabatch (pinned by device_lookup='on')"
    n = int(getattr(ctx, "n_device_eligible", 0))
    if n < MIN_DEVICE_LOOKUP_BATCH:
        return False, (
            f"tiny batch ({n} device-eligible lookups < "
            f"{MIN_DEVICE_LOOKUP_BATCH}): host walk keeps it"
        )
    if getattr(ctx, "platform", "cpu") == "cpu":
        return False, (
            "cpu platform: host tier walk is the measured default; "
            "promotable when priced cheaper or forced"
        )
    return True, (
        f"device backend with {n} device-eligible lookups: one "
        "megabatched launch beats per-query host round-trips"
    )


LOOKUP_PLANS = [
    Plan(
        name="device_lookup", entry="serve", priority=10,
        qualify=_qual_device_lookup,
        price_routes=("lookup-device",),
        forced=lambda cfg: getattr(cfg, "device_lookup", "auto") == "on",
        force_overrides={"device_lookup": "on"},
    ),
    Plan(
        name="host_lookup", entry="serve", priority=20,
        qualify=lambda ctx: (True, "unconditional host tier-walk fallback"),
        price_routes=("lookup-host",),
        forced=lambda cfg: getattr(cfg, "device_lookup", "auto") == "off",
        force_overrides={"device_lookup": "off"},
    ),
]


def tune_record(
    *,
    knob: str,
    value,
    platform: str,
    num_nodes: int,
    num_edges: int,
    batch: int = 1,
    plan: str | None = None,
    wall_s: float | None = None,
    compute_s: float | None = None,
    censored: bool = False,
    budget_s: float | None = None,
    rung: int | None = None,
    label: str = "tuner",
    event: str | None = None,
    reason: str | None = None,
) -> dict:
    """The ``kind: "tune"`` profile-store record (ISSUE 19): one per
    tuner probe (``event=None``) or per demotion (``event="demote"``,
    written by ``bench_regress`` when a promoted value regresses past
    the noise band). Probe records are what marks a value
    "tuner-promoted" in provenance; a CENSORED probe (killed at its
    wall-clock cap) carries no measured wall and can never promote —
    ``observe.tuning`` skips it by construction. The CostModel's fit
    ignores the kind entirely, so probes never distort route pricing;
    the ordinary ``kind:"plan"``/``"solve"`` records the probe solve
    itself lands are the calibration."""
    out = {
        "ts": time.time(),
        "kind": "tune",
        "label": label,
        "platform": platform,
        "nodes": int(num_nodes),
        "edges": int(num_edges),
        "batch": int(batch),
        "knob": knob,
        "value": value,
    }
    if event is not None:
        out["event"] = event
    if plan is not None:
        out["plan"] = plan
    if censored:
        out["censored"] = True
    if budget_s is not None:
        out["budget_s"] = float(budget_s)
    if rung is not None:
        out["rung"] = int(rung)
    if reason is not None:
        out["reason"] = reason
    measured = {}
    if wall_s is not None:
        measured["wall_s"] = float(wall_s)
    if compute_s is not None:
        measured["compute_s"] = float(compute_s)
    if measured:
        out["measured"] = measured
    return out


def plan_record(
    decision: dict,
    *,
    label: str,
    platform: str,
    num_nodes: int,
    num_edges: int,
    batch: int,
    wall_s: float | None = None,
    compute_s: float | None = None,
) -> dict:
    """The ``kind: "plan"`` profile-store record: one per solve whose
    dispatch went through the registry — what ``bench_regress.py``
    ingests (a planner that starts picking slower routes flags as a
    wall regression against its shape bucket's history) and what the
    auto-tuner reads parameter outcomes from (``observe.tuning``)."""
    out = {
        "ts": time.time(),
        "kind": "plan",
        "label": label,
        "platform": platform,
        "nodes": int(num_nodes),
        "edges": int(num_edges),
        "batch": int(batch),
        "route": decision.get("built") or decision.get("chosen"),
        "chosen": decision.get("chosen"),
        "reason": decision.get("reason"),
        "candidates": decision.get("candidates"),
        "params": decision.get("params") or {},
    }
    if decision.get("degraded"):
        out["degraded"] = True
    measured = {}
    if wall_s is not None:
        measured["wall_s"] = float(wall_s)
    if compute_s is not None:
        measured["compute_s"] = float(compute_s)
    if measured:
        out["measured"] = measured
    return out
