"""Benchmark harness for the five attested configs (SURVEY.md §2 #14,
BASELINE.json:6-12) plus the ``dimacs_ny_scrambled`` companion row (the
road-graph config under a scrambled vertex labeling — the honest proxy
for the real DIMACS file, whose labeling is not a lattice order).

Each config is a callable returning a result record; the harness times the
solve, folds in the attested edges-relaxed counters (BASELINE.json:2
"edges-relaxed/sec/chip"), and emits one JSON line per run. ``pjtpu bench``
is the CLI front end; ``update_baseline_md`` rewrites the measured-numbers
table in BASELINE.md.

Dataset stand-ins (zero-egress environment — the public files cannot be
downloaded): DIMACS-NY road graph -> ``grid2d`` lattice with matching node
count/diameter profile and safe negative weights; SNAP ego-Facebook ->
R-MAT scale-12 power-law graph with matching node/edge counts. Swap in the
real files via ``dimacs:<path>`` / ``snap:<path>`` specs when present.

Presets scale every config: ``smoke`` (CI, seconds), ``mini`` (single-chip
sanity), ``full`` (the attested benchmark sizes).
"""

from __future__ import annotations

import contextvars
import dataclasses
import json
import time
from pathlib import Path
from typing import Callable

import numpy as np

from paralleljohnson_tpu.utils.reductions import finite_frac as _finite_frac

# Per-config telemetry for a bench pass (``run(..., telemetry_dir=...)``):
# a contextvar because the config callables build their own solvers via
# ``_solver`` — the pass sets it around each config so every solver the
# config constructs records into that config's flight file.
_BENCH_TELEMETRY: contextvars.ContextVar = contextvars.ContextVar(
    "pj_bench_telemetry", default=None
)

# Cost-observatory profile store for a bench pass (``run(...,
# profile_dir=...)``): same contextvar pattern — every solver a config
# builds captures compiled costs and appends its profile records there,
# so a bench pass leaves the calibration artifact behind by default.
_BENCH_PROFILE: contextvars.ContextVar = contextvars.ContextVar(
    "pj_bench_profile", default=None
)


@dataclasses.dataclass
class BenchRecord:
    config: str
    backend: str
    preset: str
    wall_s: float
    edges_relaxed: int
    edges_relaxed_per_sec: float
    n_chips: int
    detail: dict

    def as_json_line(self) -> str:
        d = dataclasses.asdict(self)
        d["edges_relaxed_per_sec_per_chip"] = (
            self.edges_relaxed_per_sec / max(self.n_chips, 1)
        )
        return json.dumps(d)


# -- sizing tables -----------------------------------------------------------

_PRESETS = ("smoke", "mini", "full")

_SIZES = {
    #                 smoke            mini              full (attested)
    "er1k_apsp":     dict(n=64,        mini_n=256,       full_n=1000),
    "dimacs_ny_bf":  dict(rows=24,     mini_rows=96,     full_rows=515),
    "dimacs_ny_scrambled": dict(rows=24, mini_rows=96,   full_rows=515),
    "dimacs_ny_scrambled_pred": dict(rows=24, mini_rows=96, full_rows=515),
    "ego_fb_nsource": dict(scale=8,    mini_scale=10,    full_scale=12,
                          sources=16,  mini_sources=64,  full_sources=512),
    "rmat_apsp":     dict(scale=8,     mini_scale=12,    full_scale=20,
                          sources=8,   mini_sources=32,  full_sources=128),
    "rmat_apsp_pipelined": dict(scale=8, mini_scale=12,  full_scale=20,
                          sources=32,  mini_sources=64,  full_sources=128),
    "batch_small":   dict(count=32,    mini_count=512,   full_count=10000),
    "dense_apsp_fw": dict(n=96,        mini_n=384,       full_n=2048),
    "dirty_window": dict(rows=24,      mini_rows=48,     full_rows=96,
                          sources=2,   mini_sources=4,   full_sources=4,
                          rscale=8,    mini_rscale=9,    full_rscale=12),
    "planner_dispatch": dict(rows=16,  mini_rows=32,     full_rows=96,
                          rscale=7,    mini_rscale=9,    full_rscale=12,
                          dense_n=64,  mini_dense_n=128, full_dense_n=256,
                          sources=4,   mini_sources=4,   full_sources=8),
    # mini/full sit past the 512 seed tile so the pad-to-V challenger
    # wins a single-block FW pass vs the seed's 2x2 blocked sweep —
    # the promotion the acceptance demands; smoke stays below it and
    # demonstrates the no-promotion-within-band rule instead.
    "planner_tuning": dict(n=256,      mini_n=576,       full_n=640,
                          probe_s=30.0, mini_probe_s=45.0,
                          full_probe_s=90.0,
                          bucket_s=120.0, mini_bucket_s=180.0,
                          full_bucket_s=360.0),
    "serve_queries": dict(n=256,       mini_n=1024,      full_n=4096,
                          queries=200, mini_queries=2000, full_queries=20000,
                          clients=16,  mini_clients=16,  full_clients=32),
    "serve_overload": dict(rows=12,    mini_rows=20,     full_rows=40,
                          clients=4,   mini_clients=6,   full_clients=8,
                          overload_s=2.5, mini_overload_s=4.0,
                          full_overload_s=6.0,
                          cooldown_s=3.5, mini_cooldown_s=5.0,
                          full_cooldown_s=6.0),
    "serve_fleet":   dict(rows=10,     mini_rows=14,     full_rows=24,
                          clients=3,   mini_clients=4,   full_clients=6,
                          duration_s=2.5, mini_duration_s=4.0,
                          full_duration_s=8.0),
    "distributed_fleet": dict(n=96,    mini_n=1024,      full_n=4096,
                          workers=2,   mini_workers=3,   full_workers=4),
    "incremental_update": dict(n=96,   mini_n=1024,      full_n=4096,
                          k=2,         mini_k=6,         full_k=12),
    "approx_apsp":   dict(n=256,       mini_n=4096,      full_n=16384,
                          sources=32,  mini_sources=128, full_sources=256),
}


def _sz(config: str, key: str, preset: str):
    table = _SIZES[config]
    if preset == "smoke":
        return table[key]
    return table[f"{preset}_{key}"]


def _n_chips() -> int:
    import jax

    return max(1, len(jax.devices()))


def _platform() -> str:
    import jax

    return jax.default_backend()


def _solver(backend: str, **cfg_overrides):
    from paralleljohnson_tpu.config import SolverConfig
    from paralleljohnson_tpu.solver import ParallelJohnsonSolver

    cfg_overrides.setdefault("telemetry", _BENCH_TELEMETRY.get())
    cfg_overrides.setdefault("profile_store", _BENCH_PROFILE.get())
    return ParallelJohnsonSolver(SolverConfig(backend=backend, **cfg_overrides))


def _routes(res) -> dict:
    """Compact resolved-kernel-route tag for a bench row's detail (e.g.
    ``"bellman_ford:gs,fanout:vm-blocked"``) — keeps before/after kernel
    comparisons reconstructable across measurement rounds (round-3
    verdict weak #8). Empty for backends that don't report routes.
    Also folds in the resilience counters when any recovery actually
    fired (retries / OOM batch degradations / watchdog abandons), so a
    row measured through a degraded path is identifiable as such — a
    clean-looking wall-clock from a solve that silently halved its batch
    twice is NOT a measurement of the intended configuration."""
    out = {}
    routes = getattr(res.stats, "routes_by_phase", None)
    if routes:
        out["route"] = ",".join(f"{k}:{v}" for k, v in sorted(routes.items()))
    s = res.stats
    if getattr(s, "retries", 0):
        out["retries"] = s.retries
    if getattr(s, "oom_degradations", 0):
        out["oom_degradations"] = s.oom_degradations
        out["final_batch"] = s.final_batch
    if getattr(s, "abandoned_stages", None):
        out["abandoned_stages"] = list(s.abandoned_stages)
    # Pipeline overlap accounting (round-9): a row that claims a wall-
    # clock win must be attributable to overlap (overlap_saved_s > 0
    # with the download/ckpt costs it hid), not to noise.
    for key in ("download_s", "ckpt_wait_s", "overlap_saved_s"):
        val = float(getattr(s, key, 0.0) or 0.0)
        if val:
            out[key] = round(val, 4)
    # Cost-observatory attribution (ISSUE 7): the roofline bound and the
    # captured analytic totals ride in the row detail, so a regression
    # flag on this row arrives pre-attributed (bench_regress reads
    # exactly these keys).
    roof = getattr(s, "roofline", None)
    if roof and roof.get("bound") and roof["bound"] != "unknown":
        out["roofline_bound"] = roof["bound"]
    cost = getattr(s, "analytic_cost", None)
    if cost and cost.get("captures"):
        out["analytic_flops"] = round(float(cost["flops"]), 1)
        out["analytic_bytes"] = round(float(cost["bytes_accessed"]), 1)
    if getattr(s, "predicted_s", None) is not None:
        out["predicted_s"] = round(float(s.predicted_s), 6)
    # Convergence-observatory summary (ISSUE 9): total iterations ride
    # at top level — bench_regress grades them like walls (a route
    # silently converging slower is a perf bug even when wall noise
    # hides it) — with the trajectory shape numbers beside them.
    conv = getattr(s, "convergence", None)
    if conv:
        out["iterations"] = sum(
            int(c.get("iterations", 0)) for c in conv.values()
        )
        out["convergence"] = {
            phase: {
                "iterations": c.get("iterations"),
                "frontier_half_life": c.get("frontier_half_life"),
                "tail_fraction": round(
                    float(c.get("tail_fraction", 0.0)), 4
                ),
                "jfr_skippable_edge_frac": round(
                    float(c.get("jfr_skippable_edge_frac", 0.0)), 4
                ),
            }
            for phase, c in conv.items()
        }
    return out


# -- the five configs --------------------------------------------------------


def bench_er1k_apsp(backend: str, preset: str) -> BenchRecord:
    """Config 1 (BASELINE.json:7): Johnson APSP on an ER graph
    (full: 1k nodes, p=0.01) — the correctness-scale reference config."""
    from paralleljohnson_tpu.graphs import erdos_renyi

    n = _sz("er1k_apsp", "n", preset)
    g = erdos_renyi(n, 0.01 if n >= 256 else 0.1, seed=42)
    solver = _solver(backend)
    solver.solve(g)  # warm compile caches
    t0 = time.perf_counter()
    res = solver.solve(g)
    wall = time.perf_counter() - t0
    return BenchRecord(
        "er1k_apsp", backend, preset, wall,
        res.stats.edges_relaxed, res.stats.edges_relaxed / wall, _n_chips(),
        {"nodes": g.num_nodes, "edges": g.num_real_edges,
         "finite_frac": _finite_frac(res.dist), **_routes(res)},
    )


def bench_dimacs_ny_bf(backend: str, preset: str) -> BenchRecord:
    """Config 2 (BASELINE.json:8): standalone Bellman-Ford SSSP on a
    negative-weight road graph (high-diameter sweep stress). Stand-in:
    ``grid2d`` lattice (see module docstring)."""
    from paralleljohnson_tpu.graphs import grid2d

    rows = _sz("dimacs_ny_bf", "rows", preset)
    g = grid2d(rows, rows, negative_fraction=0.2, seed=7)
    solver = _solver(backend)
    solver.sssp(g, 0)  # warm
    t0 = time.perf_counter()
    res = solver.sssp(g, 0)
    wall = time.perf_counter() - t0
    return BenchRecord(
        "dimacs_ny_bf", backend, preset, wall,
        res.stats.edges_relaxed, res.stats.edges_relaxed / wall, _n_chips(),
        {"nodes": g.num_nodes, "edges": g.num_real_edges,
         "sweeps": res.stats.iterations_by_phase.get("bellman_ford", 0),
         "reached_frac": _finite_frac(res.dist), **_routes(res)},
    )


def bench_dimacs_ny_scrambled(backend: str, preset: str) -> BenchRecord:
    """Config 2b (round-5 verdict next #3): the SAME road-graph SSSP as
    ``dimacs_ny_bf`` but with the vertex labels uniformly permuted —
    the honest proxy for the real DIMACS file, whose labeling is not a
    lattice order. The natural row-major grid labeling qualifies the
    DIA stencil route; a real file's does not, so THIS row is what the
    attested config would actually measure: auto must decline DIA here
    and serve the solve through the irregular-labeling routes (bucket
    on TPU, frontier on CPU)."""
    from paralleljohnson_tpu.graphs import grid2d, permute_labels

    rows = _sz("dimacs_ny_scrambled", "rows", preset)
    g = permute_labels(
        grid2d(rows, rows, negative_fraction=0.2, seed=7), seed=11
    )
    solver = _solver(backend)
    solver.sssp(g, 0)  # warm
    t0 = time.perf_counter()
    res = solver.sssp(g, 0)
    wall = time.perf_counter() - t0
    return BenchRecord(
        "dimacs_ny_scrambled", backend, preset, wall,
        res.stats.edges_relaxed, res.stats.edges_relaxed / wall, _n_chips(),
        {"nodes": g.num_nodes, "edges": g.num_real_edges,
         "sweeps": res.stats.iterations_by_phase.get("bellman_ford", 0),
         "reached_frac": _finite_frac(res.dist), **_routes(res)},
    )


def bench_dimacs_ny_scrambled_pred(backend: str, preset: str) -> BenchRecord:
    """Config 2c (round-7 tentpole): the scrambled road-graph SSSP with
    ``--predecessors`` — the solve that used to abandon the whole fast
    kernel family for the legacy argmin sweep. Times the tight-edge
    extraction route AND (jax only) the legacy sweep on the same graph,
    so BENCH/BASELINE record the pred-route speedup and the exact
    edges-examined ratio (extraction adds one O(E) pass; the sweep pays
    iterations x E)."""
    from paralleljohnson_tpu.graphs import grid2d, permute_labels

    rows = _sz("dimacs_ny_scrambled_pred", "rows", preset)
    g = permute_labels(
        grid2d(rows, rows, negative_fraction=0.2, seed=7), seed=11
    )
    solver = _solver(backend)
    solver.sssp(g, 0, predecessors=True)  # warm
    t0 = time.perf_counter()
    res = solver.sssp(g, 0, predecessors=True)
    wall = time.perf_counter() - t0
    detail = {
        "nodes": g.num_nodes, "edges": g.num_real_edges,
        "reached_frac": _finite_frac(res.dist), **_routes(res),
    }
    if backend == "jax":
        legacy = _solver(backend, pred_extraction=False)
        legacy.sssp(g, 0, predecessors=True)  # warm
        t0 = time.perf_counter()
        lres = legacy.sssp(g, 0, predecessors=True)
        detail["legacy_sweep_wall_s"] = round(time.perf_counter() - t0, 6)
        detail["legacy_sweep_edges_relaxed"] = lres.stats.edges_relaxed
        detail["pred_route_speedup"] = round(
            detail["legacy_sweep_wall_s"] / max(wall, 1e-9), 3
        )
    return BenchRecord(
        "dimacs_ny_scrambled_pred", backend, preset, wall,
        res.stats.edges_relaxed, res.stats.edges_relaxed / wall, _n_chips(),
        detail,
    )


def bench_ego_fb_nsource(backend: str, preset: str) -> BenchRecord:
    """Config 3 (BASELINE.json:9): batched N-source fan-out on a
    non-negative power-law graph (ego-Facebook profile). Stand-in: R-MAT
    (see module docstring)."""
    from paralleljohnson_tpu.graphs import rmat

    scale = _sz("ego_fb_nsource", "scale", preset)
    n_sources = _sz("ego_fb_nsource", "sources", preset)
    g = rmat(scale, 16, seed=3)
    rng = np.random.default_rng(0)
    sources = np.sort(rng.choice(g.num_nodes, size=min(n_sources, g.num_nodes),
                                 replace=False))
    solver = _solver(backend)
    solver.multi_source(g, sources)  # warm
    t0 = time.perf_counter()
    res = solver.multi_source(g, sources)
    wall = time.perf_counter() - t0
    return BenchRecord(
        "ego_fb_nsource", backend, preset, wall,
        res.stats.edges_relaxed, res.stats.edges_relaxed / wall, _n_chips(),
        {"nodes": g.num_nodes, "edges": g.num_real_edges,
         "sources": len(sources), **_routes(res)},
    )


def bench_rmat_apsp(backend: str, preset: str) -> BenchRecord:
    """Config 4 (BASELINE.json:10): Johnson APSP on R-MAT (full: scale 20;
    scale 22 via PJ_BENCH_RMAT_SCALE). The full distance matrix is not
    materializable at scale 22 (~70 TB, SURVEY.md §7); per the attested
    metric the harness solves a source subset and reduces rows to a
    checksum — rows stream through, never accumulate."""
    import os

    from paralleljohnson_tpu.graphs import rmat

    default_scale = _sz("rmat_apsp", "scale", preset)
    scale = int(os.environ.get("PJ_BENCH_RMAT_SCALE", 0)) or default_scale
    # A non-default scale gets its own row name so e.g. the RMAT-22 run
    # never overwrites the scale-20 row in BASELINE.md (rows merge by
    # (config, backend, preset)).
    name = "rmat_apsp" if scale == default_scale else f"rmat_apsp_s{scale}"
    n_sources = _sz("rmat_apsp", "sources", preset)
    g = rmat(scale, 16, seed=42)
    rng = np.random.default_rng(1)
    sources = np.sort(rng.choice(g.num_nodes, size=n_sources, replace=False))
    solver = _solver(backend)
    small = sources[: max(2, n_sources // 8)]
    solver.solve_reduced(g, sources=small, reduce_rows="checksum")  # warm
    t0 = time.perf_counter()
    res = solver.solve_reduced(g, sources=sources, reduce_rows="checksum")
    wall = time.perf_counter() - t0
    checksum = float(sum(res.values))
    return BenchRecord(
        name, backend, preset, wall,
        res.stats.edges_relaxed, res.stats.edges_relaxed / wall, _n_chips(),
        {"scale": scale, "nodes": g.num_nodes, "edges": g.num_real_edges,
         "sources": n_sources, "rows_checksum": checksum, **_routes(res)},
    )


def bench_rmat_apsp_pipelined(backend: str, preset: str) -> BenchRecord:
    """Config 4b (round-9 tentpole): the rmat fan-out as a MULTI-batch
    checkpointed solve, measured serial (``pipeline_depth=1``) vs
    double-buffered (``pipeline_depth=2``) on the same graph — so
    BENCH/BASELINE can attribute any s22-class improvement to
    compute/transfer/IO overlap rather than noise. The timed row is the
    pipelined run; the detail column records the serial wall, the
    speedup, and the overlap accounting (``overlap_saved_s`` > 0 is the
    proof the win came from the pipeline). Rows are cross-checked
    bitwise between the two runs — a pipelined result that drifted is a
    bug, not a measurement."""
    import tempfile

    from paralleljohnson_tpu.graphs import rmat

    scale = _sz("rmat_apsp_pipelined", "scale", preset)
    n_sources = _sz("rmat_apsp_pipelined", "sources", preset)
    g = rmat(scale, 16, seed=42)
    rng = np.random.default_rng(1)
    sources = np.sort(
        rng.choice(g.num_nodes, size=min(n_sources, g.num_nodes),
                   replace=False)
    )
    bs = max(1, len(sources) // 4)  # >= 4 batches: the window needs work
    # Warm WITHOUT a checkpoint dir: a warmed checkpoint would let the
    # timed runs resume instead of computing.
    _solver(backend, source_batch_size=bs).multi_source(g, sources)
    with tempfile.TemporaryDirectory() as d_serial, \
            tempfile.TemporaryDirectory() as d_pipe:
        serial = _solver(backend, source_batch_size=bs, pipeline_depth=1,
                         checkpoint_dir=d_serial)
        t0 = time.perf_counter()
        sres = serial.multi_source(g, sources)
        serial_wall = time.perf_counter() - t0
        pipe = _solver(backend, source_batch_size=bs, pipeline_depth=2,
                       checkpoint_dir=d_pipe)
        t0 = time.perf_counter()
        res = pipe.multi_source(g, sources)
        wall = time.perf_counter() - t0
    detail = {
        "scale": scale, "nodes": g.num_nodes, "edges": g.num_real_edges,
        "sources": len(sources), "source_batch": bs,
        "serial_wall_s": round(serial_wall, 6),
        "pipeline_speedup": round(serial_wall / max(wall, 1e-9), 3),
        **_routes(res),
    }
    if not np.array_equal(np.asarray(sres.dist), np.asarray(res.dist)):
        detail["failed"] = "pipelined rows != serial rows"
    return BenchRecord(
        "rmat_apsp_pipelined", backend, preset, wall,
        res.stats.edges_relaxed, res.stats.edges_relaxed / wall, _n_chips(),
        detail,
    )


def bench_batch_small(backend: str, preset: str) -> BenchRecord:
    """Config 5 (BASELINE.json:11): many-small-graphs vmapped APSP
    (full: 10k random 256-node graphs)."""
    from paralleljohnson_tpu.graphs import random_graph_batch

    count = _sz("batch_small", "count", preset)
    nodes = 64 if preset == "smoke" else 256
    graphs = random_graph_batch(count, nodes, 8.0 / nodes, seed=0)
    solver = _solver(backend)
    try:
        # Time the vectorized batch kernel itself, with results left where
        # the backend computed them (the [count, V, V] block is ~2.6 GB at
        # the full preset — downloading it is not part of the solve).
        # Completion is guaranteed by the iteration-count sync inside
        # batch_apsp plus an explicit block on device arrays.
        from paralleljohnson_tpu.graphs import stack_graphs

        batch = stack_graphs(graphs)
        if backend == "jax":
            # Full-shape warm: the jit cache is shape-keyed. Host backends
            # have no compile cache — a full warm would just double the
            # (minutes-long at the full preset) run for nothing.
            solver.backend.batch_apsp(batch)
        else:
            solver.backend.batch_apsp(stack_graphs(graphs[: max(2, count // 16)]))
        t0 = time.perf_counter()
        res = solver.backend.batch_apsp(batch)
        if not isinstance(res.dist, np.ndarray):
            import jax

            jax.block_until_ready(res.dist)
        wall = time.perf_counter() - t0
        edges = res.edges_relaxed
    except NotImplementedError:
        # Backends without a vectorized path: time the per-graph fallback.
        solver.solve_batch(graphs[: max(2, count // 16)])  # warm
        t0 = time.perf_counter()
        results = solver.solve_batch(graphs)
        wall = time.perf_counter() - t0
        # The per-graph fallback gives each result its own stats object;
        # sum over distinct objects to report the whole batch.
        edges = sum(
            s.edges_relaxed
            for s in {id(r.stats): r.stats for r in results}.values()
        )
    return BenchRecord(
        "batch_small", backend, preset, wall,
        edges, edges / wall, _n_chips(),
        {"graphs": count, "nodes_each": nodes},
    )


def bench_dense_apsp_fw(backend: str, preset: str) -> BenchRecord:
    """Config 7 (round-13 tentpole): dense full APSP via the blocked
    min-plus Floyd-Warshall route (``ops.fw``, route ``fw``/``fw-tile``)
    vs the min-plus squaring route on the SAME graph — the B=V workload
    the repo is named for, exercised end to end on the MXU shape. The
    graph's weights are small integers so every f32 path sum is exact:
    the two routes are checked BITWISE, not allclose — a blocked
    schedule that dropped a k-phase would be caught, not tolerated. The
    timed row is the FW run; detail records the squaring wall, the
    speedup, and the exact tropical-MAC ratio (~log2 V by construction,
    both counters on the same padded scale), plus the roofline bound
    and analytic FLOPs via the shared ``_routes`` folding — this is the
    first bench row whose roofline must read ``mxu``."""
    from paralleljohnson_tpu.graphs import erdos_renyi

    n = _sz("dense_apsp_fw", "n", preset)
    g = erdos_renyi(n, 0.1, seed=21)
    rng = np.random.default_rng(22)
    g = g.with_weights(
        rng.integers(1, 10, g.num_real_edges).astype(np.float32)
    )
    fw_solver = _solver(backend, fw=True, mesh_shape=(1,))
    fw_solver.solve(g)  # warm compile caches
    t0 = time.perf_counter()
    res = fw_solver.solve(g)
    wall = time.perf_counter() - t0
    sq_solver = _solver(backend, fw=False, dense_threshold=n,
                        dense_min_density=0, mesh_shape=(1,))
    sq_solver.solve(g)  # warm
    t0 = time.perf_counter()
    sres = sq_solver.solve(g)
    sq_wall = time.perf_counter() - t0
    detail = {
        "nodes": g.num_nodes, "edges": g.num_real_edges,
        "squaring_wall_s": round(sq_wall, 6),
        "fw_speedup": round(sq_wall / max(wall, 1e-9), 3),
        "squaring_edges_relaxed": sres.stats.edges_relaxed,
        "work_ratio_sq_over_fw": round(
            sres.stats.edges_relaxed / max(res.stats.edges_relaxed, 1), 3
        ),
        **_routes(res),
    }
    if not np.array_equal(np.asarray(res.matrix), np.asarray(sres.matrix)):
        detail["failed"] = "blocked-FW rows != squaring rows (bitwise)"
    return BenchRecord(
        "dense_apsp_fw", backend, preset, wall,
        res.stats.edges_relaxed, res.stats.edges_relaxed / wall, _n_chips(),
        detail,
    )


def bench_planner_dispatch(backend: str, preset: str) -> BenchRecord:
    """Config 13 (ISSUE 14 tentpole): does the priced planner pick the
    measured-fastest qualified route? Three contrasting graphs —
    scrambled road grid (irregular low-degree sweep territory), R-MAT
    power-law (hub-heavy sweep territory), and a dense small-V graph
    (dense/FW territory). Per graph:

    1. every candidate plan is FORCED via its registry
       ``force_overrides`` and measured on the same sources, its solve
       + plan records landing in a fresh throwaway profile store (the
       calibration the planner will price from);
    2. the auto planner then dispatches the same solve; the row's
       detail records the pick, the measured-fastest auto-qualified
       plan, whether the pick is the fastest or within the planner's
       noise band of it (the acceptance criterion), and that the
       planner solve's distances are BITWISE-identical to the forced
       run of the same plan (registry dispatch never changes a
       route's arithmetic).

    Non-jax backends have no planner registry; their row records the
    plain solve with an explicit marker."""
    import tempfile

    from paralleljohnson_tpu.graphs import (
        erdos_renyi,
        grid2d,
        permute_labels,
        rmat,
    )

    rows = _sz("planner_dispatch", "rows", preset)
    rscale = _sz("planner_dispatch", "rscale", preset)
    dense_n = _sz("planner_dispatch", "dense_n", preset)
    n_sources = _sz("planner_dispatch", "sources", preset)

    grid = permute_labels(
        grid2d(rows, rows, negative_fraction=0.0, seed=7), seed=11
    )
    power = rmat(rscale, edge_factor=8, seed=5)
    dense = erdos_renyi(dense_n, 0.5, seed=3)
    # smoke keeps the candidate sets lean (every forced plan pays its
    # compiles — the CI suite-budget); mini/full measure the full
    # contrast set including the dw and GS schedules.
    grid_plans = (
        ["vm", "sweep-sm"] if preset == "smoke"
        else ["vm", "sweep-sm", "vm-blocked+dw", "gs"]
    )
    workloads = [
        # (name, graph, batch, candidate plan names to force-measure)
        ("scrambled_grid", grid, n_sources, grid_plans),
        ("rmat", power, n_sources, ["vm", "sweep-sm"]),
        ("dense_small_v", dense, dense.num_nodes, ["dense", "fw"]),
    ]

    if backend != "jax":
        t0 = time.perf_counter()
        res = _solver(backend).multi_source(
            grid, np.arange(n_sources, dtype=np.int64)
        )
        wall = time.perf_counter() - t0
        return BenchRecord(
            "planner_dispatch", backend, preset, wall,
            res.stats.edges_relaxed, res.stats.edges_relaxed / wall,
            _n_chips(),
            {"skipped": "planner registry is jax-only; plain solve "
                        "recorded", **_routes(res)},
        )

    from paralleljohnson_tpu.backends.jax_backend import FANOUT_PLANS
    from paralleljohnson_tpu.planner import PLANNER_NOISE_BAND

    plan_by_name = {p.name: p for p in FANOUT_PLANS}
    per_graph = {}
    total_wall = 0.0
    total_edges = 0
    headline_res = None
    for name, g, b, candidates in workloads:
        store = tempfile.mkdtemp(prefix=f"pj_planner_{name}_")
        sources = np.arange(min(b, g.num_nodes), dtype=np.int64)
        measured, dists, skipped = {}, {}, {}
        for plan_name in candidates:
            plan = plan_by_name[plan_name]
            overrides = dict(plan.force_overrides)
            try:
                forced = _solver(
                    backend, profile_store=store, planner=False,
                    **overrides,
                )
                forced.multi_source(g, sources)  # warm compiles
                t0 = time.perf_counter()
                fres = forced.multi_source(g, sources)
                dt = time.perf_counter() - t0
            except Exception as e:  # noqa: BLE001 — a declined plan is data
                skipped[plan_name] = f"{type(e).__name__}: {e}"
                continue
            measured[plan_name] = {
                "route": fres.stats.routes_by_phase.get("fanout"),
                "wall_ms": round(dt * 1e3, 3),
                "wall_s": dt,
            }
            dists[plan_name] = np.asarray(fres.dist)
        # All plans solve the same problem: any pairwise disagreement
        # beyond float-order noise is a dispatch bug, not noise.
        names = sorted(dists)
        agree = all(
            np.allclose(dists[names[0]], dists[m],
                        rtol=1e-5, atol=1e-5, equal_nan=True)
            for m in names[1:]
        )
        auto = _solver(backend, profile_store=store)
        auto.multi_source(g, sources)  # warm (also lands records)
        t0 = time.perf_counter()
        res = auto.multi_source(g, sources)
        dt = time.perf_counter() - t0
        plan_info = res.stats.plan or {}
        pick = plan_info.get("built") or plan_info.get("chosen")
        qualified = [
            c["plan"] for c in plan_info.get("candidates", [])
            if c.get("qualified")
        ]
        contest = {
            k: v["wall_s"] for k, v in measured.items() if k in qualified
        }
        fastest = min(contest, key=contest.get) if contest else None
        within = (
            contest[pick] <= contest[fastest] * (1.0 + PLANNER_NOISE_BAND)
            if pick in contest and fastest is not None else None
        )
        bitwise = (
            bool(np.array_equal(np.asarray(res.dist), dists[pick],
                                equal_nan=True))
            if pick in dists else None
        )
        per_graph[name] = {
            "nodes": g.num_nodes,
            "edges": g.num_real_edges,
            "batch": int(len(sources)),
            "measured": {
                k: {kk: vv for kk, vv in v.items() if kk != "wall_s"}
                for k, v in measured.items()
            },
            "skipped": skipped,
            "pick": pick,
            "reason": plan_info.get("reason"),
            "qualified": qualified,
            "fastest_qualified": fastest,
            "pick_within_band": within,
            "pick_bitwise_vs_forced": bitwise,
            "routes_agree": bool(agree),
            "planner_wall_ms": round(dt * 1e3, 3),
        }
        total_wall += dt
        total_edges += res.stats.edges_relaxed
        headline_res = res
    verdict = {
        "all_within_band": all(
            v["pick_within_band"] in (True, None)
            for v in per_graph.values()
        ),
        "all_bitwise": all(
            v["pick_bitwise_vs_forced"] in (True, None)
            for v in per_graph.values()
        ),
        "all_routes_agree": all(
            v["routes_agree"] for v in per_graph.values()
        ),
    }
    return BenchRecord(
        "planner_dispatch", backend, preset, total_wall,
        total_edges, total_edges / max(total_wall, 1e-9), _n_chips(),
        {"noise_band": PLANNER_NOISE_BAND, **verdict,
         "graphs": per_graph, **_routes(headline_res)},
    )


def bench_planner_tuning(backend: str, preset: str) -> BenchRecord:
    """Config 17 (ISSUE 19 tentpole): the self-proposing planner's
    propose → probe-under-budget → promote → dispatch loop, measured on
    one dense graph (FW territory) with the ``fw_tile`` knob. Two
    phases, graded in-bench (violations land in ``detail.failed``):

    - **zero budget**: ``tune_bucket`` with ``bucket_budget_s=0`` must
      touch nothing — the store stays empty and the auto dispatch is
      BITWISE-identical to today's store-less dispatch (the acceptance
      criterion that a disabled tuner changes no behavior);
    - **budgeted**: the tuner probes the hand-tuned seed tile against
      the pad-to-V tile under a hard per-probe wall cap, promotes the
      winner only past the planner's 25% noise band, and the next auto
      dispatch resolves the promoted value — verified bitwise against
      a run with that tile forced, with ``provenance_table`` reporting
      the knob as tuner-backed.

    Non-jax backends have no tuner registry; their row records the
    plain solve with an explicit marker."""
    import tempfile

    from paralleljohnson_tpu.graphs import erdos_renyi

    n = _sz("planner_tuning", "n", preset)
    probe_s = _sz("planner_tuning", "probe_s", preset)
    bucket_s = _sz("planner_tuning", "bucket_s", preset)
    g = erdos_renyi(n, 0.3, seed=3)

    if backend != "jax":
        t0 = time.perf_counter()
        res = _solver(backend).solve(g)
        wall = time.perf_counter() - t0
        return BenchRecord(
            "planner_tuning", backend, preset, wall,
            res.stats.edges_relaxed, res.stats.edges_relaxed / wall,
            _n_chips(),
            {"skipped": "tuner registry is jax-only; plain solve "
                        "recorded", **_routes(res)},
        )

    from paralleljohnson_tpu.config import SolverConfig
    from paralleljohnson_tpu.observe.tuning import (
        DEFAULT_FW_TILE,
        TUNE_NOISE_BAND,
        resolve_param,
    )
    from paralleljohnson_tpu.tuner import provenance_table, tune_bucket

    pad = ((n + 127) // 128) * 128
    candidates = {"fw_tile": sorted({DEFAULT_FW_TILE, pad})}
    failed = []
    fw_cfg = dict(fw=True, mesh_shape=(1,))

    # Phase A — zero tuning budget must be a perfect no-op.
    store_a = tempfile.mkdtemp(prefix="pj_tune_zero_")
    summary_a = tune_bucket(
        g, store_dir=store_a, config=SolverConfig(backend=backend),
        knobs=["fw_tile"], candidates=candidates,
        probe_budget_s=probe_s, bucket_budget_s=0.0,
    )
    store_untouched = not (Path(store_a) / "profiles.jsonl").exists()
    if summary_a.get("probes", -1) != 0 or not store_untouched:
        failed.append("zero-budget tuner touched the store")
    plain = _solver(backend, profile_store=None, **fw_cfg).solve(g)
    with_store = _solver(backend, profile_store=store_a, **fw_cfg).solve(g)
    zero_bitwise = bool(np.array_equal(
        np.asarray(plain.dist), np.asarray(with_store.dist),
        equal_nan=True,
    ))
    if not zero_bitwise:
        failed.append("zero-budget dispatch diverged from store-less")

    # Phase B — budgeted probes, band-gated promotion, auto dispatch.
    store_b = tempfile.mkdtemp(prefix="pj_tune_probe_")
    t0 = time.perf_counter()
    summary_b = tune_bucket(
        g, store_dir=store_b,
        config=SolverConfig(backend=backend, profile_store=store_b),
        knobs=["fw_tile"], candidates=candidates,
        probe_budget_s=probe_s, bucket_budget_s=bucket_s,
    )
    tune_wall = time.perf_counter() - t0
    knob = summary_b["knobs"].get("fw_tile", {})
    eff_tile, eff_source = resolve_param(
        "fw_tile", None, DEFAULT_FW_TILE,
        config=SolverConfig(backend=backend, profile_store=store_b),
        platform=_platform(), num_nodes=g.num_nodes,
        num_edges=g.num_real_edges,
        validate=lambda t: isinstance(t, int) and t >= 128 and t % 128 == 0,
    )
    if knob.get("promoted") and eff_tile != knob.get("winner"):
        failed.append(
            f"dispatch resolved tile {eff_tile}, tuner promoted "
            f"{knob.get('winner')}"
        )
    prov = {
        row["knob"]: row for row in provenance_table(
            store_dir=store_b, num_nodes=g.num_nodes,
            num_edges=g.num_real_edges,
            config=SolverConfig(backend=backend, profile_store=store_b),
        )
    }.get("fw_tile", {})
    if knob.get("promoted") and prov.get("source") != "tuner-promoted":
        failed.append(
            f"provenance says {prov.get('source')!r} for a promoted knob"
        )

    auto = _solver(backend, profile_store=store_b, **fw_cfg)
    auto.solve(g)  # warm compiles on the resolved tile
    t0 = time.perf_counter()
    res = auto.solve(g)
    dispatch_wall = time.perf_counter() - t0
    forced = _solver(
        backend, profile_store=None, fw_tile=int(eff_tile), **fw_cfg
    ).solve(g)
    dispatch_bitwise = bool(np.array_equal(
        np.asarray(res.dist), np.asarray(forced.dist), equal_nan=True,
    ))
    if not dispatch_bitwise:
        failed.append("auto dispatch diverged from forced tuned tile")

    total_wall = tune_wall + dispatch_wall
    detail = {
        "noise_band": TUNE_NOISE_BAND,
        "zero_budget": {
            "summary": summary_a, "store_untouched": store_untouched,
            "bitwise_vs_storeless": zero_bitwise,
        },
        "tuning": {
            "probes": summary_b.get("probes"),
            "censored": summary_b.get("censored"),
            "probe_budget_s": probe_s,
            "bucket_budget_s": bucket_s,
            "tune_wall_s": round(tune_wall, 4),
            "fw_tile": knob,
        },
        "provenance": prov,
        "dispatch": {
            "tile": int(eff_tile), "source": eff_source,
            "bitwise_vs_forced": dispatch_bitwise,
            "wall_ms": round(dispatch_wall * 1e3, 3),
        },
        **_routes(res),
    }
    if failed:
        detail["failed"] = "; ".join(failed)
    return BenchRecord(
        "planner_tuning", backend, preset, total_wall,
        res.stats.edges_relaxed,
        res.stats.edges_relaxed / max(total_wall, 1e-9), _n_chips(),
        detail,
    )


def bench_dirty_window(backend: str, preset: str) -> BenchRecord:
    """Config 10 (ISSUE 13 tentpole): dirty-window compacted relaxation
    vs the plain batched route on the SAME graphs — the bench that
    converts the convergence observatory's measured skippable fraction
    into recorded wall-clock. Two workloads:

    - the scrambled road grid (the convergence-evidence shape) at batch
      width: the dw route (forced) vs the plain dispatch (dw disabled),
      BITWISE-checked, with the exact examined/skipped edge counters
      (examined from the kernel's split counter; skipped = the plain
      run's exact examined total minus dw's) and the speedup;
    - the rmat power-law preset: the same comparison where the
      trajectory is flat-ish — the workload the dispatch must DECLINE.

    The detail also records the trajectory-driven dispatch loop end to
    end: an instrumented plain solve writes its trajectory into a
    throwaway profile store, and ``dw_decision`` over that store must
    engage for the grid and decline for rmat — the "never blindly"
    acceptance, exercised on real records."""
    import tempfile

    from paralleljohnson_tpu.graphs import grid2d, permute_labels, rmat

    rows = _sz("dirty_window", "rows", preset)
    n_sources = _sz("dirty_window", "sources", preset)
    rscale = _sz("dirty_window", "rscale", preset)
    g = permute_labels(
        grid2d(rows, rows, negative_fraction=0.0, seed=7), seed=11
    )
    rng = np.random.default_rng(0)
    sources = np.sort(
        rng.choice(g.num_nodes, size=min(n_sources, g.num_nodes),
                   replace=False)
    )

    def timed(graph, srcs, **cfg):
        solver = _solver(backend, mesh_shape=(1,), **cfg)
        solver.multi_source(graph, srcs)  # warm compile caches
        t0 = time.perf_counter()
        res = solver.multi_source(graph, srcs)
        return res, time.perf_counter() - t0

    res, wall = timed(g, sources, dirty_window=True)
    pres, plain_wall = timed(g, sources, dirty_window=False)
    examined = res.stats.edges_relaxed
    plain_examined = pres.stats.edges_relaxed
    detail = {
        "nodes": g.num_nodes, "edges": g.num_real_edges,
        "sources": len(sources),
        "plain_wall_s": round(plain_wall, 6),
        "dw_speedup": round(plain_wall / max(wall, 1e-9), 3),
        "examined_edges": int(examined),
        "plain_examined_edges": int(plain_examined),
        "skipped_edges": int(plain_examined - examined),
        "skip_frac": round(
            1.0 - examined / max(plain_examined, 1), 4
        ),
        **_routes(res),
    }
    if not np.array_equal(np.asarray(res.dist), np.asarray(pres.dist)):
        detail["failed"] = "dw rows != plain rows (bitwise)"

    # R-MAT companion: the workload whose trajectory must DECLINE dw.
    gr = rmat(rscale, 16, seed=3)
    rsources = np.sort(
        rng.choice(gr.num_nodes, size=min(n_sources, gr.num_nodes),
                   replace=False)
    )
    rres, rwall = timed(gr, rsources, dirty_window=True)
    rpres, rplain_wall = timed(gr, rsources, dirty_window=False)
    detail["rmat"] = {
        "nodes": gr.num_nodes, "edges": gr.num_real_edges,
        "dw_wall_s": round(rwall, 6),
        "plain_wall_s": round(rplain_wall, 6),
        "dw_speedup": round(rplain_wall / max(rwall, 1e-9), 3),
        "skip_frac": round(
            1.0 - rres.stats.edges_relaxed
            / max(rpres.stats.edges_relaxed, 1), 4
        ),
    }
    if not np.array_equal(np.asarray(rres.dist), np.asarray(rpres.dist)):
        detail["failed"] = "rmat dw rows != plain rows (bitwise)"

    # Trajectory-driven dispatch, end to end on real records (jax only:
    # host backends record no trajectories).
    if backend == "jax":
        from paralleljohnson_tpu.backends import get_backend
        from paralleljohnson_tpu.config import SolverConfig

        with tempfile.TemporaryDirectory() as d:
            for graph, srcs in ((g, sources), (gr, rsources)):
                _solver(
                    backend, dirty_window=False, convergence=True,
                    profile_store=d, mesh_shape=(1,),
                ).multi_source(graph, srcs)
            be = get_backend("jax", SolverConfig(
                profile_store=d, mesh_shape=(1,)
            ))
            detail["dispatch"] = {
                "grid": be._dw_decision(be.upload(g), len(sources)),
                "rmat": be._dw_decision(be.upload(gr), len(rsources)),
            }
    return BenchRecord(
        "dirty_window", backend, preset, wall,
        res.stats.edges_relaxed,
        res.stats.edges_relaxed / max(wall, 1e-9), _n_chips(),
        detail,
    )


def bench_serve_queries(backend: str, preset: str) -> BenchRecord:
    """Config 6 (round-11 tentpole, concurrent since ISSUE 12): the
    query-serving layer measured as a TRAFFIC-BEARING SERVICE — K >= 4
    client threads offering a sustained request rate, not one thread
    replaying as fast as it can. A checkpoint-backed store is warmed
    with a quarter of the sources (one scheduled exact batch), a
    landmark index covers the rest, then a seeded 85/15 hit/approx mix
    is split across K paced client threads against ONE shared engine
    (the thread-safety contract under test is the deployment shape).
    The offered rate is calibrated from a short closed-loop probe
    (~70% of measured serial capacity — sustained load, not overload),
    each client sleeps to its own send schedule, and the detail column
    reports the STREAMING histogram p50/p99 with their one-bucket error
    bounds plus the SLO burn verdict — the row is the CPU twin of the
    staged `jax-serve-bench` stage.

    Since ISSUE 16 the row also carries a ``lookup`` contrast block:
    the SAME request mix replayed closed-loop by K >= 16 concurrent
    clients through a shared :class:`MicroBatcher`, once with the host
    tier walk forced and once with the device megabatch path forced.
    The two response sets must be BITWISE identical (the planner's
    bit-for-bit promise, asserted here, not assumed), and the block
    records both walls, the speedup, and the auto planner's why-line
    for this platform."""
    import json as _json
    import tempfile
    import threading

    from paralleljohnson_tpu.graphs import erdos_renyi
    from paralleljohnson_tpu.observe.live import SLO
    from paralleljohnson_tpu.serve import (
        LandmarkIndex,
        MicroBatcher,
        QueryEngine,
        TileStore,
    )

    n = _sz("serve_queries", "n", preset)
    n_queries = _sz("serve_queries", "queries", preset)
    n_clients = _sz("serve_queries", "clients", preset)
    g = erdos_renyi(n, 8.0 / n, seed=13)
    cfg_kwargs = dict(telemetry=_BENCH_TELEMETRY.get(),
                      profile_store=_BENCH_PROFILE.get())
    from paralleljohnson_tpu.config import SolverConfig

    cfg = SolverConfig(backend=backend, **cfg_kwargs)
    slo = SLO(name="serve", latency_ms=250.0, availability=0.999,
              rules=((60.0, 15.0, 14.4), (300.0, 60.0, 6.0)))
    rng = np.random.default_rng(17)
    warm_sources = np.sort(rng.choice(n, size=max(8, n // 4), replace=False))
    with tempfile.TemporaryDirectory() as d:
        store = TileStore(d, g, hot_rows=max(8, n // 8), warm_rows=n)
        landmarks = LandmarkIndex.build(g, k=8, config=cfg, seed=0)
        QueryEngine(g, store, landmarks=landmarks, config=cfg,
                    miss_policy="landmark").warm(warm_sources)
        # Separate calibration engine over the same store, then a fresh
        # engine for the timed loop: neither the warm batch's nor the
        # closed-loop probe's latencies may pollute the measurement.
        probe_engine = QueryEngine(g, store, landmarks=landmarks,
                                   config=cfg, miss_policy="landmark")
        engine = QueryEngine(g, store, landmarks=landmarks, config=cfg,
                             miss_policy="landmark", slo=slo)
        warm_set = set(int(s) for s in warm_sources)
        cold_pool = np.array(sorted(set(range(n)) - warm_set), np.int64)
        hit = rng.random(n_queries) < 0.85
        srcs = np.where(
            hit,
            rng.choice(warm_sources, size=n_queries),
            rng.choice(cold_pool, size=n_queries),
        )
        dsts = rng.integers(0, n, size=n_queries)
        requests = [
            {"id": i, "source": int(srcs[i]), "dst": int(dsts[i])}
            for i in range(n_queries)
        ]
        probe = requests[: min(64, n_queries)]
        batch_size = 16  # per-client aggregation batch
        t0 = time.perf_counter()
        for i in range(0, len(probe), batch_size):  # closed-loop probe
            probe_engine.query_batch(probe[i : i + batch_size])
        serial_qps = len(probe) / max(time.perf_counter() - t0, 1e-9)
        offered_qps = max(50.0, 0.7 * serial_qps)

        # Split the mix round-robin across K clients; each paces its
        # batches to the shared offered rate (open-loop per client: a
        # slow server makes latency grow, it does not slow the offers).
        per_client = offered_qps / n_clients
        slices = [requests[k::n_clients] for k in range(n_clients)]
        barrier = threading.Barrier(n_clients + 1)
        errors: list[BaseException] = []

        def client(k: int) -> None:
            try:
                mine = slices[k]
                barrier.wait()
                start = time.perf_counter()
                sent = 0
                for i in range(0, len(mine), batch_size):
                    due = start + sent / per_client
                    delay = due - time.perf_counter()
                    if delay > 0:
                        time.sleep(delay)
                    batch = mine[i : i + batch_size]
                    engine.query_batch(batch)
                    sent += len(batch)
            except BaseException as e:  # noqa: BLE001 — surface, don't hang
                errors.append(e)

        threads = [threading.Thread(target=client, args=(k,),
                                    name=f"bench-client-{k}")
                   for k in range(n_clients)]
        for t in threads:
            t.start()
        barrier.wait()
        t0 = time.perf_counter()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        if errors:
            raise errors[0]
        pcts = engine.stats.percentiles()
        verdict = engine.metrics.slo(slo).evaluate()
        latency = verdict.get("latency") or {}
        detail = {
            "nodes": g.num_nodes, "edges": g.num_real_edges,
            "queries": n_queries, "landmarks": landmarks.k,
            "warm_sources": len(warm_sources),
            "clients": n_clients,
            "offered_per_s": round(offered_qps, 2),
            "queries_per_s": round(n_queries / max(wall, 1e-9), 2),
            # Streaming-histogram estimates with their one-bucket error
            # bounds (never an unflagged approximation — ISSUE 12).
            "p50_ms": round(pcts["p50_ms"], 4),
            "p50_err_ms": round(pcts["p50_err_ms"], 4),
            "p99_ms": round(pcts["p99_ms"], 4),
            "p99_err_ms": round(pcts["p99_err_ms"], 4),
            "slo": {
                "p99_target_ms": slo.latency_ms,
                "availability": slo.availability,
                "verdict": "burn" if verdict["burning"] else "ok",
                "burn_rate": verdict["burn_rate"],
                "p99_met": latency.get("met"),
            },
            "hit_rate": round(engine.store.hit_rate(), 4),
            "approx_frac": round(
                engine.stats.approx_answers
                / max(1, engine.stats.queries_total), 4,
            ),
        }
        # -- host vs device lookup contrast (ISSUE 16) --------------------
        # Same store, same mix, closed loop: K clients hammer a shared
        # MicroBatcher so the engine sees device-width batches, once
        # per forced path. Wall times compare the LOOKUP paths alone.
        def _lookup_phase(mode: str) -> tuple[float, list, "QueryEngine"]:
            eng = QueryEngine(g, store, landmarks=landmarks, config=cfg,
                              miss_policy="landmark", device_lookup=mode)
            mb = MicroBatcher(eng, max_width=max(16, n_clients))
            out: list = [None] * len(requests)
            gate = threading.Barrier(n_clients + 1)
            errs: list[BaseException] = []

            def worker(k: int) -> None:
                try:
                    gate.wait()
                    for req in requests[k::n_clients]:
                        out[req["id"]] = mb.submit(req)
                except BaseException as e:  # noqa: BLE001
                    errs.append(e)

            ts = [threading.Thread(target=worker, args=(k,),
                                   name=f"lookup-{mode}-{k}")
                  for k in range(n_clients)]
            for t in ts:
                t.start()
            gate.wait()
            t1 = time.perf_counter()
            for t in ts:
                t.join()
            dt = time.perf_counter() - t1
            if errs:
                raise errs[0]
            return dt, out, eng

        wall_host, host_out, host_eng = _lookup_phase("off")
        wall_dev, dev_out, dev_eng = _lookup_phase("on")
        bitwise = (_json.dumps(host_out, sort_keys=True)
                   == _json.dumps(dev_out, sort_keys=True))
        # What would AUTO pick here? One batch through an auto engine
        # records the planner's decision + why-line for this platform.
        auto_eng = QueryEngine(g, store, landmarks=landmarks, config=cfg,
                               miss_policy="landmark")
        auto_eng.query_batch(requests[: max(16, n_clients)])
        detail["lookup"] = {
            "clients": n_clients,
            "wall_host_s": round(wall_host, 4),
            "wall_device_s": round(wall_dev, 4),
            "speedup": round(wall_host / max(wall_dev, 1e-9), 3),
            "bitwise_identical": bitwise,
            "device_lookups": dev_eng.stats.device_lookups,
            "host_lookups": host_eng.stats.host_lookups,
            "auto_decision": auto_eng.last_lookup_decision,
        }
        for e in (host_eng, dev_eng, auto_eng):
            e.close()
        if not bitwise:
            # A parity break is a wrong-answer bug, not a slow bench.
            detail["failed"] = "host/device lookup answers diverged"

        # Leave the live snapshot beside the flight recorder when the
        # pass runs with telemetry (tpu_round3_run.sh preserves the dir;
        # the slo-report stage reads it offline).
        tel = _BENCH_TELEMETRY.get()
        if tel is not None and getattr(tel, "trace_dir", None):
            engine.metrics.write_snapshot(
                Path(tel.trace_dir) / "serve_live.json"
            )
        engine.close()
    # The serving row's headline is queries/sec, not edges/sec — the
    # edges columns stay zero rather than conflating warm-solve compute
    # with the request loop being measured.
    return BenchRecord(
        "serve_queries", backend, preset, wall, 0, 0.0, _n_chips(), detail,
    )


def bench_serve_overload(backend: str, preset: str) -> BenchRecord:
    """Config 13 (ISSUE 15 tentpole): the traffic FRONT END measured at
    ~2x its own calibrated capacity, through real TCP sockets — the
    designed-overload contract under test, not throughput:

    - accepted traffic stays in SLO (the latency target is calibrated
      from a closed-loop mixed probe; admission bounds the queue, so
      accepted p99 cannot grow without bound);
    - overload produces explicit ``overloaded`` rejections (never an
      unbounded queue), which burn the availability budget and trip the
      multi-window burn alert;
    - the burn alert engages CERTIFIED shedding: a nonzero-but-bounded
      fraction of answers comes back ``{shed: true, exact: false,
      max_error: <finite>}`` and every one is verified against the
      direct solve's matrix (|answer - exact| <= max_error);
    - every non-shed answer is verified BITWISE against the same matrix;
    - when offered load drops back below capacity (the cooldown phase),
      shedding disengages — zero shed answers in the late cooldown.

    Violations land in ``detail["failed"]`` (the row is the assertion).
    The graph is a strongly connected 2-D lattice so every landmark
    bound is finite — a shed answer with an infinite bound would be
    honest but useless, and this bench demands useful degradation."""
    import socket as _socket
    import tempfile
    import threading

    from paralleljohnson_tpu.config import SolverConfig
    from paralleljohnson_tpu.graphs import grid2d
    from paralleljohnson_tpu.observe.live import SLO
    from paralleljohnson_tpu.serve import (
        LandmarkIndex,
        QueryEngine,
        ServeFrontend,
        TileStore,
    )
    from paralleljohnson_tpu.solver import ParallelJohnsonSolver

    rows = _sz("serve_overload", "rows", preset)
    n_clients = _sz("serve_overload", "clients", preset)
    overload_s = float(_sz("serve_overload", "overload_s", preset))
    cooldown_s = float(_sz("serve_overload", "cooldown_s", preset))
    g = grid2d(rows, rows, seed=41)
    n = g.num_nodes
    cfg = SolverConfig(backend=backend, telemetry=_BENCH_TELEMETRY.get(),
                       profile_store=_BENCH_PROFILE.get())
    # The oracle every answer is graded against (f32 rows, losslessly
    # widened — the same values the engine serves).
    exact = np.asarray(ParallelJohnsonSolver(
        SolverConfig(backend=backend)).solve(g).matrix)

    rng = np.random.default_rng(43)
    warm = np.sort(rng.choice(n, size=max(8, n // 4), replace=False))
    rest = np.array(sorted(set(range(n)) - set(map(int, warm))), np.int64)
    probe_cold = rest[: max(1, len(rest) // 3)]
    phase_cold = rest[max(1, len(rest) // 3):]

    with tempfile.TemporaryDirectory() as d:
        store = TileStore(d, g, hot_rows=max(8, n // 8), warm_rows=n)
        landmarks = LandmarkIndex.build(g, k=8, config=cfg, seed=0)
        QueryEngine(g, store, landmarks=landmarks, config=cfg).warm(warm)

        # Capacity + latency calibration: a mixed (80% warm hit / 20%
        # cold miss -> scheduled solve) closed loop through a throwaway
        # engine over the same store. The SLO latency target is 10x the
        # probe's p99 — generous enough that bounded-queue accepted
        # traffic holds it, tight enough that an unbounded queue would
        # not.
        probe_engine = QueryEngine(g, store, landmarks=landmarks,
                                   config=cfg, stats_interval_s=0)
        probe_n = 64
        t0 = time.perf_counter()
        for i in range(probe_n):
            src = (int(probe_cold[i % len(probe_cold)]) if i % 5 == 4
                   else int(rng.choice(warm)))
            probe_engine.query_batch(
                [{"source": src, "dst": int(rng.integers(n))}])
        capacity_qps = probe_n / max(time.perf_counter() - t0, 1e-9)
        probe_p99 = probe_engine.stats.percentiles()["p99_ms"]
        probe_engine.close()
        latency_target_ms = max(50.0, 10.0 * probe_p99)

        slo = SLO(name="serve", latency_ms=latency_target_ms,
                  latency_pct=99.0, availability=0.9,
                  rules=((20.0, 1.5, 2.0),))
        engine = QueryEngine(g, store, landmarks=landmarks, config=cfg,
                             miss_policy="solve", slo=slo,
                             stats_interval_s=0)
        frontend = ServeFrontend(
            engine, max_connections=2 * n_clients, max_inflight=2,
            shed_policy="landmark", retry_after_ms=25,
        ).start()
        host, port = frontend.address

        results: dict[str, list] = {"overload": [], "cooldown": []}
        res_lock = threading.Lock()
        client_errors: list[BaseException] = []

        def client(k: int, phase: str, rate: float, duration_s: float,
                   barrier) -> None:
            # Closed-loop paced: wait until the next send is due, send,
            # read the one response line (every request gets exactly
            # one — a missing line is a hung connection and fails the
            # bench via the socket timeout).
            try:
                sock = _socket.create_connection((host, port), timeout=30)
                sock.settimeout(30)
                f = sock.makefile("rw", encoding="utf-8", newline="\n")
                json.loads(f.readline())  # protocol header
                crng = np.random.default_rng(1000 * (1 + k) + len(phase))
                local = []
                sent = 0
                barrier.wait()
                start = time.perf_counter()
                while True:
                    elapsed = time.perf_counter() - start
                    if elapsed >= duration_s:
                        break
                    delay = sent / rate - elapsed
                    if delay > 0:
                        time.sleep(delay)
                    src = (int(crng.choice(warm)) if crng.random() < 0.7
                           else int(phase_cold[crng.integers(
                               len(phase_cold))]))
                    dst = int(crng.integers(n))
                    f.write(json.dumps(
                        {"id": sent, "source": src, "dst": dst}) + "\n")
                    f.flush()
                    resp = json.loads(f.readline())
                    local.append((src, dst, resp,
                                  time.perf_counter() - start))
                    sent += 1
                f.close()
                sock.close()
                with res_lock:
                    results[phase].extend(local)
            except BaseException as e:  # noqa: BLE001 — surface, don't hang
                client_errors.append(e)

        def run_phase(phase: str, total_rate: float,
                      duration_s: float) -> None:
            barrier = threading.Barrier(n_clients)
            threads = [
                threading.Thread(
                    target=client,
                    args=(k, phase, total_rate / n_clients, duration_s,
                          barrier),
                    name=f"overload-client-{phase}-{k}")
                for k in range(n_clients)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()

        t0 = time.perf_counter()
        run_phase("overload", 2.0 * capacity_qps, overload_s)
        shed_after_overload = engine.stats.shed_answers
        rejected_after_overload = engine.stats.rejected
        run_phase("cooldown", 0.3 * capacity_qps, cooldown_s)
        wall = time.perf_counter() - t0
        if client_errors:
            frontend.drain()
            raise client_errors[0]

        # -- grade every response against the oracle ----------------------
        failures: list[str] = []
        all_resps = results["overload"] + results["cooldown"]
        shed_n = rejected_n = exact_n = 0
        for src, dst, r, _ in all_resps:
            if "error" in r:
                if r["error"] in ("overloaded", "deadline", "draining"):
                    rejected_n += 1
                else:
                    failures.append(f"unexpected error answer: {r}")
                continue
            want = float(exact[src, dst])
            if r.get("shed"):
                shed_n += 1
                if r.get("exact") is not False or "max_error" not in r:
                    failures.append(f"shed answer not flagged: {r}")
                    continue
                err = float(r["max_error"])
                if not np.isfinite(err):
                    failures.append(
                        f"shed answer with non-finite max_error: {r}")
                elif abs(float(r["distance"]) - want) > err + 1e-9:
                    failures.append(
                        f"shed answer outside certified bound: "
                        f"|{r['distance']} - {want}| > {err}")
            elif r.get("exact") is True:
                exact_n += 1
                if float(r["distance"]) != want:
                    failures.append(
                        f"non-shed answer not bitwise: s={src} t={dst} "
                        f"{r['distance']} != {want}")
            else:
                failures.append(f"unflagged approximate answer: {r}")

        accepted = shed_n + exact_n
        shed_frac = shed_n / max(1, accepted)
        if shed_after_overload == 0:
            failures.append(
                "overload phase shed nothing — the burn alert never "
                "engaged at 2x capacity")
        if rejected_after_overload == 0:
            failures.append(
                "overload phase rejected nothing — admission control "
                "never bit at 2x capacity")
        if shed_frac >= 0.5:
            failures.append(
                f"shed fraction {shed_frac:.3f} unbounded — most "
                "answers degraded (shedding should be a tail, not the "
                "service)")
        # Disengagement: zero shed answers in the late cooldown (the
        # short burn window has drained by then).
        shed_late = sum(
            1 for _, _, r, t in results["cooldown"]
            if r.get("shed") and t >= cooldown_s / 2
        )
        if shed_late:
            failures.append(
                f"{shed_late} shed answers in the late cooldown — "
                "shedding failed to disengage below capacity")
        verdict = engine.slo_tracker().evaluate()
        latency = verdict.get("latency") or {}
        if latency.get("met") is False:
            failures.append(
                f"accepted-traffic p{latency.get('pct')} "
                f"{latency.get('observed_ms')} ms missed the "
                f"{latency.get('target_ms')} ms target")

        pcts = engine.stats.percentiles()
        stats = engine.stats
        detail = {
            "nodes": n, "edges": g.num_real_edges,
            "clients": n_clients,
            "capacity_per_s": round(capacity_qps, 2),
            "offered_x": 2.0,
            "overload_s": overload_s, "cooldown_s": cooldown_s,
            "accepted": accepted,
            "rejected": rejected_n,
            "deadline_drops": stats.deadline_drops,
            "shed_answers": shed_n,
            "shed_frac": round(shed_frac, 4),
            "shed_late_cooldown": shed_late,
            "exact_bitwise_checked": exact_n,
            "p50_ms": round(pcts["p50_ms"], 4),
            "p50_err_ms": round(pcts["p50_err_ms"], 4),
            "p99_ms": round(pcts["p99_ms"], 4),
            "p99_err_ms": round(pcts["p99_err_ms"], 4),
            "slo": {
                "p99_target_ms": round(latency_target_ms, 3),
                "availability": slo.availability,
                "verdict": "burn" if verdict["burning"] else "ok",
                "burn_rate": verdict["burn_rate"],
                "p99_met": latency.get("met"),
            },
        }
        if failures:
            detail["failed"] = failures[:10]
        tel = _BENCH_TELEMETRY.get()
        if tel is not None and getattr(tel, "trace_dir", None):
            engine.metrics.write_snapshot(
                Path(tel.trace_dir) / "serve_overload_live.json"
            )
        frontend.drain()  # flushes snapshots + closes the engine
    return BenchRecord(
        "serve_overload", backend, preset, wall, 0, 0.0, _n_chips(),
        detail,
    )


def bench_serve_fleet(backend: str, preset: str) -> BenchRecord:
    """Config 17 (ISSUE 18 tentpole): the REPLICATED serve fleet under a
    kill-one-replica chaos drill, through real TCP sockets and real
    subprocesses — the failover contract under test, not throughput:

    - three ``pjtpu serve`` replica processes register into a shared
      fleet directory via heartbeated membership records and all serve
      the same pre-solved checkpoint;
    - a consistent-hash :class:`FleetRouter` forwards every client line
      to the owning replica; mid-traffic one replica is SIGKILLed and
      the router must re-publish the routing table minus the corpse and
      re-route the dead replica's sources within one heartbeat lapse
      (``reroute_lapse_s`` is the graded axis — a slower failover flags
      the regression gate);
    - zero hung clients (every request gets exactly one response line or
      an explicit admission error), zero unflagged approximations, and
      every non-shed answer is verified BITWISE against the direct
      solve's matrix — a misrouted query is only colder, never wrong;
    - the per-replica latency histograms merge into one service-level
      SLO verdict (:func:`observe.top.gather_ops` fleet view) which must
      be in-SLO for the row to pass;
    - request tracing end to end (ISSUE 20): router + every replica run
      with flight recorders, the kill-survivor probe's answer must
      assemble (``observe.trace.assemble``) into ONE single-rooted
      timeline spanning router and replica, at least one trace must show
      the retry hop (a ``forward`` span with ``attempt >= 2``) across
      the kill, and a post-drill query for the one deliberately
      unsolved source must carry the scheduled ``serve_solve`` in its
      assembled trace.

    Violations land in ``detail["failed"]`` (the row is the assertion)."""
    import os as _os
    import signal as _signal
    import socket as _socket
    import subprocess as _subprocess
    import sys as _sys
    import tempfile
    import threading

    from paralleljohnson_tpu.config import SolverConfig
    from paralleljohnson_tpu.graphs import grid2d
    from paralleljohnson_tpu.observe.top import gather_ops
    from paralleljohnson_tpu.serve import (
        FleetRouter,
        QueryEngine,
        TileStore,
        read_routing,
    )

    rows = _sz("serve_fleet", "rows", preset)
    n_clients = _sz("serve_fleet", "clients", preset)
    duration_s = float(_sz("serve_fleet", "duration_s", preset))
    n_replicas = 3
    heartbeat_s = 0.25
    stale_after_s = 1.5
    lapse_budget_s = stale_after_s + 2.0
    # The registry loader for "grid:rows=R,cols=R" is
    # grid2d(R, R, negative_fraction=0.0, seed=0) — the oracle MUST be
    # digest-identical to what the replica subprocesses load.
    graph_name = f"grid:rows={rows},cols={rows}"
    g = grid2d(rows, rows, negative_fraction=0.0, seed=0)
    n = g.num_nodes
    cfg = SolverConfig(backend=backend, telemetry=_BENCH_TELEMETRY.get(),
                       profile_store=_BENCH_PROFILE.get())
    from paralleljohnson_tpu.solver import ParallelJohnsonSolver

    exact = np.asarray(ParallelJohnsonSolver(
        SolverConfig(backend=backend)).solve(g).matrix)

    failures: list[str] = []
    procs: list[_subprocess.Popen] = []
    with tempfile.TemporaryDirectory() as td:
        fleet_dir = Path(td) / "fleet"
        store_dir = Path(td) / "store"
        trace_root = Path(td) / "trace"
        # Pre-solve the checkpoint once; every replica serves it
        # cold/warm so non-shed answers are bitwise-reproducible. Source
        # n-1 is deliberately left UNSOLVED (clients never query it):
        # the post-drill solve probe queries it so its assembled trace
        # must contain the scheduled serve_solve hop (ISSUE 20).
        seed_store = TileStore(str(store_dir), g, hot_rows=max(8, n // 8),
                               warm_rows=n)
        seed_engine = QueryEngine(g, seed_store, config=cfg,
                                  stats_interval_s=0)
        seed_engine.warm(np.arange(n - 1))
        seed_engine.close()

        env = dict(_os.environ)
        repo_root = str(Path(__file__).resolve().parents[1])
        env["PYTHONPATH"] = _os.pathsep.join(
            p for p in (repo_root, env.get("PYTHONPATH")) if p)
        # Replica subprocesses always run on CPU (the distributed
        # launch.py convention): the checkpoint is pre-solved, so
        # replicas only SERVE rows — three processes must never fight
        # over a single-tenant accelerator.
        env["JAX_PLATFORMS"] = "cpu"

        def spawn_replica(i: int) -> tuple[_subprocess.Popen, dict]:
            p = _subprocess.Popen(
                [_sys.executable, "-m", "paralleljohnson_tpu.cli",
                 "serve", graph_name,
                 "--listen", "127.0.0.1:0",
                 "--store-dir", str(store_dir),
                 "--backend", backend,
                 "--fleet-dir", str(fleet_dir),
                 "--replica-id", f"replica-{i}",
                 "--replica-heartbeat", str(heartbeat_s),
                 "--slo-p99-ms", "2000",
                 "--stats-interval", "0.5",
                 "--trace-dir", str(trace_root / f"replica-{i}")],
                env=env, stdout=_subprocess.PIPE,
                stderr=_subprocess.DEVNULL, text=True)
            line = p.stdout.readline()
            try:
                ann = json.loads(line)
            except (json.JSONDecodeError, ValueError):
                p.kill()
                raise RuntimeError(
                    f"replica {i} printed no announce line: {line!r}")
            return p, ann

        router = None
        router_tel = None
        t0 = time.perf_counter()
        try:
            anns = []
            for i in range(n_replicas):
                p, ann = spawn_replica(i)
                procs.append(p)
                anns.append(ann)
            from paralleljohnson_tpu.utils.telemetry import Telemetry

            router_tel = Telemetry.create(
                trace_dir=trace_root / "router", label="router")
            router = FleetRouter(
                str(fleet_dir), stale_after_s=stale_after_s,
                refresh_interval_s=heartbeat_s / 2,
                retry_after_ms=25,
                telemetry=router_tel,
            ).start()
            host, port = router.address()
            table = router.table
            epoch_before = table.epoch if table is not None else 0
            if table is None or len(
                    {table.owner(str(s)) for s in range(n)}) < 2:
                failures.append(
                    "routing table did not spread ownership across "
                    "replicas")

            # The victim owns the probe source — after the SIGKILL the
            # probe measures how long its traffic stays dark.
            probe_src = 0
            victim_rid = table.owner(str(probe_src)) if table else None
            victim_i = int(victim_rid.rsplit("-", 1)[1]) if victim_rid \
                else 0

            results: list[tuple[int, int, dict]] = []
            res_lock = threading.Lock()
            client_errors: list[BaseException] = []
            kill_at_s = duration_s * 0.4
            lapse_box: dict = {}

            def client(k: int) -> None:
                # Closed-loop paced through the ROUTER: one response
                # line per request, in order — a missing line hangs the
                # socket timeout and fails the bench.
                try:
                    sock = _socket.create_connection((host, port),
                                                     timeout=30)
                    sock.settimeout(30)
                    f = sock.makefile("rw", encoding="utf-8",
                                      newline="\n")
                    json.loads(f.readline())  # router header
                    crng = np.random.default_rng(2000 + k)
                    local = []
                    sent = 0
                    rate = 40.0  # per client, well below capacity
                    start = time.perf_counter()
                    while True:
                        elapsed = time.perf_counter() - start
                        if elapsed >= duration_s:
                            break
                        delay = sent / rate - elapsed
                        if delay > 0:
                            time.sleep(delay)
                        # n-1 is the reserved never-solved source — the
                        # solve probe's, not client traffic's.
                        src = int(crng.integers(n - 1))
                        dst = int(crng.integers(n))
                        f.write(json.dumps(
                            {"id": sent, "source": src, "dst": dst,
                             "client_id": f"bench-{k}"}) + "\n")
                        f.flush()
                        local.append((src, dst, json.loads(f.readline())))
                        sent += 1
                    f.close()
                    sock.close()
                    with res_lock:
                        results.extend(local)
                except BaseException as e:  # noqa: BLE001 — surface it
                    client_errors.append(e)

            def killer() -> None:
                # SIGKILL the probe source's owner mid-traffic, then
                # probe that source through the router until it answers
                # exactly again: kill -> first good answer is the
                # re-route lapse.
                time.sleep(kill_at_s)
                procs[victim_i].send_signal(_signal.SIGKILL)
                procs[victim_i].wait()
                t_kill = time.perf_counter()
                deadline = t_kill + max(10.0, 3 * lapse_budget_s)
                while time.perf_counter() < deadline:
                    try:
                        sock = _socket.create_connection((host, port),
                                                         timeout=5)
                        sock.settimeout(5)
                        f = sock.makefile("rw", encoding="utf-8",
                                          newline="\n")
                        json.loads(f.readline())
                        f.write(json.dumps(
                            {"id": 0, "source": probe_src,
                             "dst": 1}) + "\n")
                        f.flush()
                        resp = json.loads(f.readline())
                        sock.close()
                        if resp.get("error") is None:
                            lapse_box["lapse_s"] = (
                                time.perf_counter() - t_kill)
                            lapse_box["resp"] = resp
                            return
                    except (OSError, json.JSONDecodeError):
                        pass
                    time.sleep(0.05)

            threads = [threading.Thread(target=client, args=(k,),
                                        name=f"fleet-client-{k}")
                       for k in range(n_clients)]
            kt = threading.Thread(target=killer, name="fleet-killer")
            for t in threads:
                t.start()
            kt.start()
            for t in threads:
                t.join()
            kt.join()
            wall = time.perf_counter() - t0
            if client_errors:
                raise client_errors[0]

            # -- grade --------------------------------------------------
            reroute_lapse_s = lapse_box.get("lapse_s")
            if reroute_lapse_s is None:
                failures.append(
                    "dead replica's sources never answered again — "
                    "the fleet lost them for good")
            elif reroute_lapse_s > lapse_budget_s:
                failures.append(
                    f"re-route took {reroute_lapse_s:.2f}s — over the "
                    f"{lapse_budget_s:.2f}s heartbeat-lapse budget")
            probe_resp = lapse_box.get("resp")
            if probe_resp is not None and not probe_resp.get("shed"):
                want = float(exact[probe_src, 1])
                if float(probe_resp["distance"]) != want:
                    failures.append(
                        f"re-routed probe answer not bitwise: "
                        f"{probe_resp['distance']} != {want}")

            table_after = read_routing(str(fleet_dir))
            epoch_after = (table_after.epoch if table_after is not None
                           else 0)
            if epoch_after <= epoch_before:
                failures.append(
                    f"routing epoch did not advance after the kill "
                    f"({epoch_before} -> {epoch_after})")
            if table_after is not None and victim_rid in {
                    table_after.owner(str(s)) for s in range(n)}:
                failures.append(
                    "dead replica still owns sources in the "
                    "re-published routing table")

            answered = rejected = shed_n = 0
            for src, dst, r in results:
                if "error" in r:
                    if r["error"] in ("overloaded", "deadline",
                                      "draining", "unavailable"):
                        rejected += 1
                    else:
                        failures.append(f"unexpected error answer: {r}")
                    continue
                if r.get("shed"):
                    shed_n += 1
                    if r.get("exact") is not False or "max_error" not in r:
                        failures.append(f"shed answer not flagged: {r}")
                    continue
                if r.get("exact") is not True:
                    failures.append(f"unflagged approximate answer: {r}")
                    continue
                answered += 1
                want = float(exact[src, dst])
                if float(r["distance"]) != want:
                    failures.append(
                        f"non-shed answer not bitwise: s={src} t={dst} "
                        f"{r['distance']} != {want}")
            if answered == 0:
                failures.append("no exact answers at all — dead fleet")

            # -- the scheduled-solve probe (ISSUE 20) -------------------
            # Source n-1 was never pre-solved and no client queried it:
            # this one query forces the owning replica to schedule a
            # solve, whose serve_solve span must land in the assembled
            # trace below.
            solve_probe_trace = None
            try:
                sock = _socket.create_connection((host, port), timeout=15)
                sock.settimeout(15)
                f = sock.makefile("rw", encoding="utf-8", newline="\n")
                json.loads(f.readline())
                f.write(json.dumps({"id": "solve-probe",
                                    "source": n - 1, "dst": 0}) + "\n")
                f.flush()
                resp = json.loads(f.readline())
                f.close()
                sock.close()
                solve_probe_trace = resp.get("trace_id")
                if resp.get("error") is not None:
                    failures.append(f"solve probe errored: {resp}")
                elif not resp.get("shed"):
                    want = float(exact[n - 1, 0])
                    if float(resp["distance"]) != want:
                        failures.append(
                            f"solve-probe answer not bitwise: "
                            f"{resp['distance']} != {want}")
            except (OSError, ValueError) as e:
                failures.append(
                    f"solve probe failed: {type(e).__name__}: {e}")

            # -- merged fleet verdict (the top/slo_report view) ---------
            time.sleep(2 * heartbeat_s)  # let final heartbeats land
            doc = gather_ops(serve_fleet=fleet_dir,
                             stale_after_s=stale_after_s)
            sf = doc.get("serve_fleet") or {}
            merged = sf.get("merged") or {}
            if merged.get("histogram_merge_error"):
                failures.append(
                    f"fleet histogram merge degraded: "
                    f"{merged['histogram_merge_error']}")
            if merged.get("verdict") not in ("ok",):
                failures.append(
                    f"merged fleet SLO verdict "
                    f"{merged.get('verdict')!r} — expected in-SLO 'ok'")
            if len(sf.get("replicas") or {}) < n_replicas - 1:
                failures.append(
                    "fleet view lost surviving replicas: "
                    f"{sorted(sf.get('replicas') or {})}")
        finally:
            if router is not None:
                router.drain()
            if router_tel is not None:
                router_tel.close()
            for p in procs:
                if p.poll() is None:
                    p.send_signal(_signal.SIGTERM)
            for p in procs:
                try:
                    p.wait(timeout=20)
                except _subprocess.TimeoutExpired:
                    p.kill()

        # -- assembled request traces (ISSUE 20) ------------------------
        # Every process on the request path flushed its own flight
        # JSONL (the SIGKILLed victim's may end in a torn line — the
        # loader tolerates exactly that); the join must reconstruct
        # end-to-end causality: the kill-survivor probe as ONE
        # single-rooted timeline spanning router + replica, a visible
        # retry hop, and the solve probe's scheduled serve_solve.
        from paralleljohnson_tpu.observe.trace import assemble

        try:
            asm = assemble([trace_root])
        except (OSError, ValueError) as e:
            failures.append(f"trace assembly failed: {e}")
            asm = {"processes": [], "traces": {}}
        traces = asm["traces"]
        probe_tid = (lapse_box.get("resp") or {}).get("trace_id")
        ptr = traces.get(probe_tid) if probe_tid else None
        if ptr is None:
            failures.append(
                "kill-survivor probe answer carried no assemblable "
                f"trace (trace_id={probe_tid!r})")
        else:
            if not ptr["single_rooted"]:
                failures.append(
                    f"probe trace {probe_tid} not single-rooted: "
                    f"roots={ptr['roots']} "
                    f"unresolved={ptr['unresolved']}")
            if ("router" not in ptr["processes"]
                    or len(ptr["processes"]) < 2):
                failures.append(
                    "probe trace does not span router + replica: "
                    f"{ptr['processes']}")
        retry_tids = [
            tid for tid, t in traces.items()
            if any(s["name"] == "forward"
                   and (s["attrs"].get("attempt") or 1) >= 2
                   for s in t["spans"])
        ]
        if not retry_tids:
            failures.append(
                "no assembled trace shows the retry hop (a forward "
                "span with attempt >= 2) across the kill")
        elif not any(traces[tid]["single_rooted"] for tid in retry_tids):
            failures.append(
                "no retried request reconstructed into a single "
                "parented trace")
        stp = traces.get(solve_probe_trace) if solve_probe_trace else None
        if stp is None:
            failures.append(
                "solve probe carried no assemblable trace "
                f"(trace_id={solve_probe_trace!r})")
        elif not any(s["name"] == "serve_solve" for s in stp["spans"]):
            failures.append(
                "solve-probe trace missing the scheduled serve_solve "
                f"span: {[s['name'] for s in stp['spans']]}")

        # The drill's tempdir dies with this function; PJ_FLEET_TRACE_OUT
        # preserves the raw flight dirs so the round-3 pass can re-run
        # the offline assembler (`trace-assemble` stage) on real fleet
        # recordings and stage the per-hop regression rows.
        keep = _os.environ.get("PJ_FLEET_TRACE_OUT")
        if keep:
            import shutil as _shutil

            _shutil.rmtree(keep, ignore_errors=True)
            try:
                _shutil.copytree(trace_root, keep)
            except OSError:
                pass

        detail = {
            "nodes": n, "edges": g.num_real_edges,
            "replicas": n_replicas,
            "clients": n_clients,
            "duration_s": duration_s,
            "heartbeat_s": heartbeat_s,
            "stale_after_s": stale_after_s,
            "reroute_lapse_s": (round(reroute_lapse_s, 4)
                                if reroute_lapse_s is not None else None),
            "reroute_budget_s": lapse_budget_s,
            "epoch_before": epoch_before,
            "epoch_after": epoch_after,
            "answered": answered,
            "rejected": rejected,
            "shed_answers": shed_n,
            "exact_bitwise_checked": answered,
            "p50_ms": merged.get("p50_ms"),
            "p99_ms": merged.get("p99_ms"),
            "p99_err_ms": merged.get("p99_err_ms"),
            "slo": merged.get("slo"),
            "verdict": merged.get("verdict"),
            "router": dict(router.stats),
            "traces_assembled": len(traces),
            "traces_single_rooted": sum(
                1 for t in traces.values() if t["single_rooted"]),
            "retry_traces": len(retry_tids),
            "probe_trace": probe_tid,
            "solve_probe_trace": solve_probe_trace,
        }
        if failures:
            detail["failed"] = failures[:10]
    return BenchRecord(
        "serve_fleet", backend, preset, wall, 0, 0.0, _n_chips(), detail,
    )


def bench_distributed_fleet(backend: str, preset: str) -> BenchRecord:
    """Config 8 (round-15 tentpole): the distributed solve fleet — N
    local CPU worker processes vs 1 on the SAME graph (README
    'Distributed fleet'). Both runs go through the full coordinator
    machinery (lease claims over the flock'd log, per-worker checkpoint
    shards, heartbeats, shard-manifest union), so the speedup number
    prices exactly what a pod deployment pays: coordination + per-
    worker process overhead vs parallel source ranges. Rows are checked
    BITWISE between the two fleets through ``fleet_rows`` (the merged
    manifests) — the graph is sparse (below the dense-density gate) and
    the source batch is pinned, so every worker resolves the same
    batch-invariant route and a drifted row is a bug, not rounding.
    The smoke preset runs the workers in-process (same machinery minus
    subprocess spawn — what tier-1 exercises); mini/full spawn real
    subprocesses. Detail records the requeue/extension counters: a
    clean run must show 0 requeues, and the host-loss drill lives in
    ``scripts/fleet_dryrun.py``, not here."""
    import tempfile

    from paralleljohnson_tpu.distributed import (
        fleet_rows,
        launch_local_fleet,
        plan_fleet,
    )
    from paralleljohnson_tpu.distributed.launch import run_in_process_fleet

    n = _sz("distributed_fleet", "n", preset)
    n_workers = _sz("distributed_fleet", "workers", preset)
    # Average degree ~4: below the dense-density gate at every preset
    # size, so every lease resolves the batch-invariant sparse fan-out.
    graph_spec = f"er:n={n},p={round(4.0 / n, 6)},seed=13"
    config = {"source_batch_size": max(16, n // 16)}
    in_process = preset == "smoke"

    def run_fleet(workers: int, d: str):
        coord = plan_fleet(
            d, graph_spec, n_workers=workers, backend=backend,
            config=config,
        )
        t0 = time.perf_counter()
        if in_process:
            report = run_in_process_fleet(coord, workers)
        else:
            report = launch_local_fleet(
                coord, workers, telemetry=_BENCH_TELEMETRY.get()
            )
        wall = time.perf_counter() - t0
        if not report.ok:
            raise RuntimeError(
                f"fleet incomplete: {report.leases_committed}/"
                f"{report.leases_total} leases committed "
                f"(worker rcs {report.worker_rcs})"
            )
        return coord, report, wall

    with tempfile.TemporaryDirectory() as d1, \
            tempfile.TemporaryDirectory() as dn:
        coord1, rep1, wall1 = run_fleet(1, d1 + "/coord")
        coordn, repn, wall = run_fleet(n_workers, dn + "/coord")
        rows1 = fleet_rows(coord1.dir)
        rowsn = fleet_rows(coordn.dir)
        detail = {
            "nodes": n, "graph_spec": graph_spec,
            "workers": n_workers,
            "worker_mode": "in-process" if in_process else "subprocess",
            "leases": repn.leases_total,
            "requeues": repn.requeues,
            "extensions": repn.extensions,
            "single_worker_wall_s": round(wall1, 6),
            "fleet_speedup": round(wall1 / max(wall, 1e-9), 3),
            "committed_by": repn.status["committed_by"],
        }
        if sorted(rows1) != sorted(rowsn):
            detail["failed"] = "fleet manifests cover different sources"
        elif not all(
            np.array_equal(rows1[s], rowsn[s]) for s in rows1
        ):
            detail["failed"] = (
                f"{n_workers}-worker rows != 1-worker rows (bitwise)"
            )
    return BenchRecord(
        "distributed_fleet", backend, preset, wall,
        repn.edges_relaxed,
        repn.edges_relaxed / max(wall, 1e-9), _n_chips(),
        detail,
    )


def bench_incremental_update(backend: str, preset: str) -> BenchRecord:
    """Config 9 (ISSUE 11 tentpole): full re-solve vs dirty-part repair
    on the SAME k-edge update (README 'Incremental updates'). A graph
    is solved into a checkpoint and its incremental state attached;
    then a k-edge update batch confined to ONE partition is applied two
    ways — a fresh full solve of the updated graph, and
    ``repair_checkpoint`` (re-close the one dirty part + the boundary
    core, re-expand affected rows). Rows are checked BITWISE (integer
    weights, so every route agrees exactly); detail records the
    speedup, the exact dirty-part counter (must stay below the part
    total — the dependency tracking is the product being measured), and
    the repair's row-action split. The one-time state build is timed
    separately (``attach_s``): it amortizes over every future update."""
    import tempfile

    from paralleljohnson_tpu.graphs import grid2d
    from paralleljohnson_tpu.incremental import repair_checkpoint
    from paralleljohnson_tpu.incremental.state import IncrementalState
    from paralleljohnson_tpu.utils.checkpoint import (
        BatchCheckpointer,
        graph_digest,
    )

    side = max(4, int(np.sqrt(_sz("incremental_update", "n", preset))))
    k_updates = _sz("incremental_update", "k", preset)
    # A lattice, not ER: the dynamic-graph workload this subsystem
    # opens is road networks (traffic updates, link failures), whose
    # small separators are what make partitioned repair cheap — an ER
    # graph's boundary core is most of the graph and would honestly
    # show repair ~ resolve. Integer weights: the bitwise
    # repair-vs-resolve check needs every route to agree exactly.
    g = grid2d(side, side, seed=17)
    n = g.num_nodes
    g = g.with_weights(np.maximum(1.0, np.rint(g.weights)).astype(np.float32))
    batch = max(16, n // 16)

    with tempfile.TemporaryDirectory() as d:
        solver = _solver(backend, checkpoint_dir=d, source_batch_size=batch)
        solver.solve(g)
        t0 = time.perf_counter()
        state = IncrementalState.build(g, config=solver.config)
        state.save(
            BatchCheckpointer(d, graph_key=graph_digest(g)).dir
        )
        attach_s = time.perf_counter() - t0

        # k updates confined to the most-populated part: the honest
        # "traffic update" shape — local change, small dirty set.
        target = int(np.bincount(state.labels).argmax())
        e = g.num_real_edges
        within = np.flatnonzero(
            (state.labels[g.src[:e]] == target)
            & (state.labels[g.indices[:e]] == target)
        )
        rng = np.random.default_rng(5)
        idx = rng.choice(within, size=min(k_updates, within.size),
                         replace=False)
        updates = [
            (int(g.src[i]), int(g.indices[i]),
             1.0 if j % 2 == 0 else float(g.weights[i]) + 3.0)
            for j, i in enumerate(idx)
        ]
        new_graph, _report = g.apply_edge_updates(updates)

        fresh_solver = _solver(backend, source_batch_size=batch)
        t0 = time.perf_counter()
        fresh = fresh_solver.solve(new_graph)
        full_wall = time.perf_counter() - t0

        t0 = time.perf_counter()
        result = repair_checkpoint(
            d, g, updates, config=solver.config, state=state
        )
        wall = time.perf_counter() - t0

        ck = BatchCheckpointer(d, graph_key=graph_digest(new_graph))
        manifest = ck.manifest()
        fresh_rows = np.asarray(fresh.matrix)
        detail = {
            "nodes": n, "edges": int(g.num_real_edges),
            "k_updates": len(updates),
            "dirty_parts": result.dirty_parts_closed,
            "parts_total": result.parts_total,
            "core_recomputed": result.core_recomputed,
            "affected_rows": result.affected_rows,
            "rows_recomputed": result.rows_recomputed,
            "rows_patched": result.rows_patched,
            "rows_copied": result.rows_copied,
            "attach_s": round(attach_s, 6),
            "full_resolve_wall_s": round(full_wall, 6),
            "repair_speedup": round(full_wall / max(wall, 1e-9), 3),
            "repair_walls": {
                "closures_s": round(result.closures_s, 6),
                "expand_s": round(result.expand_s, 6),
                "io_s": round(result.io_s, 6),
            },
        }
        if result.dirty_parts_closed >= result.parts_total:
            detail["failed"] = (
                "dirty-part counter reached the part total — the "
                "update was supposed to stay local"
            )
        elif len(manifest) != n:
            detail["failed"] = (
                f"repaired checkpoint covers {len(manifest)} of {n} "
                "sources"
            )
        else:
            seen = {}
            for fn in sorted({f for _b, f in manifest.values()}):
                srcs = ck.batch_sources(fn)
                loaded = ck.load(int(manifest[int(srcs[0])][0]), srcs)
                if loaded is None:
                    detail["failed"] = f"unreadable repaired batch {fn}"
                    break
                for i, s in enumerate(srcs):
                    seen[int(s)] = loaded[0][i]
            if "failed" not in detail and not all(
                np.array_equal(seen[s], fresh_rows[s]) for s in seen
            ):
                detail["failed"] = (
                    "repaired rows != fresh full solve (bitwise)"
                )
    return BenchRecord(
        "incremental_update", backend, preset, wall,
        result.expand_macs,
        result.expand_macs / max(wall, 1e-9), _n_chips(), detail,
    )


def bench_approx_apsp(backend: str, preset: str) -> BenchRecord:
    """Config 16 (ISSUE 17 tentpole): exact vs certified ``hopset+bf``
    on the SAME graph and source set, at ε ∈ {0.1, 0.5}. A corridor
    lattice (aspect 16), not ER: large diameter is the regime the
    hopset tier exists for — the exact routes sweep to the diameter
    (~4x a square grid's at equal V/E) while the approximate route
    pays β hops past the relay seed. Per ε the detail records construction wall,
    query wall, hopset edge count, the measured max observed error vs
    the exact matrix, and the certified bound it must sit under — a
    single entry whose measured error exceeds its certificate lands in
    ``detail.failed`` and flunks ``bench_regress`` as a contract
    failure (the certificate is the product; a violation is a bug, not
    a slow day). ``speedup`` = exact wall / (construction + query):
    the honest end-to-end ratio, construction un-amortized."""
    from paralleljohnson_tpu.graphs import grid2d
    from paralleljohnson_tpu.solver.approx import approx_apsp

    short = max(2, int(np.sqrt(_sz("approx_apsp", "n", preset) / 16)))
    g = grid2d(16 * short, short, seed=23)
    n = g.num_nodes
    n_sources = min(_sz("approx_apsp", "sources", preset), n)
    rng = np.random.default_rng(11)
    sources = np.sort(rng.choice(n, size=n_sources, replace=False))

    solver = _solver(backend)
    solver.solve(g, sources)  # warm (compile) — same discipline as er1k
    t0 = time.perf_counter()
    exact_res = solver.solve(g, sources)
    exact_wall = time.perf_counter() - t0
    exact_rows = np.asarray(exact_res.matrix, np.float64)

    detail = {
        "nodes": n, "edges": int(g.num_real_edges),
        "n_sources": int(n_sources),
        "exact_wall_s": round(exact_wall, 6),
        "exact": _routes(exact_res),
    }
    wall = exact_wall
    examined = int(exact_res.stats.edges_relaxed)
    for eps in (0.1, 0.5):
        approx_apsp(g, sources, config=solver.config, epsilon=eps)  # warm
        t0 = time.perf_counter()
        res = approx_apsp(
            g, sources, config=solver.config, epsilon=eps
        )
        approx_wall = time.perf_counter() - t0
        est = np.asarray(res.dist, np.float64)
        err = np.asarray(res.max_error, np.float64)
        # The certification contract, checked entrywise against the
        # exact matrix: wherever the certificate is finite the measured
        # error must sit under it, and a finite exact distance must
        # never be answered with an uncertified +inf.
        certified = np.isfinite(err)
        measured = np.where(
            np.isfinite(exact_rows) & np.isfinite(est),
            np.abs(est - exact_rows), 0.0,
        )
        violations = int(np.sum(certified & (measured > err)))
        wrong_inf = int(np.sum(
            certified & (np.isfinite(exact_rows) != np.isfinite(est))
        ))
        key = f"eps_{eps:g}"
        detail[key] = {
            "construction_s": round(res.stats["construction_s"], 6),
            "query_s": round(res.stats["query_s"], 6),
            "beta": res.stats["beta"],
            "hopset_edges": res.stats["hopset_edges"],
            "hopset_converged": res.stats["hopset_converged"],
            "query_converged": res.stats["query_converged"],
            "measured_max_error": round(float(measured.max()), 6),
            "certified_max_bound": (
                round(float(err[certified].max()), 6)
                if certified.any() else None
            ),
            "certified_frac": round(float(certified.mean()), 6),
            "speedup": round(exact_wall / max(approx_wall, 1e-9), 3),
        }
        if violations or wrong_inf:
            detail["failed"] = (
                f"eps={eps:g}: {violations} entries exceed their "
                f"certified bound, {wrong_inf} reachability "
                "mismatches under a finite certificate"
            )
        if eps == 0.5:
            wall = approx_wall
            examined = int(res.stats["edges_examined"])
    return BenchRecord(
        "approx_apsp", backend, preset, wall, examined,
        examined / max(wall, 1e-9), _n_chips(), detail,
    )


CONFIGS: dict[str, Callable[[str, str], BenchRecord]] = {
    "er1k_apsp": bench_er1k_apsp,
    "dimacs_ny_bf": bench_dimacs_ny_bf,
    "dimacs_ny_scrambled": bench_dimacs_ny_scrambled,
    "dimacs_ny_scrambled_pred": bench_dimacs_ny_scrambled_pred,
    "ego_fb_nsource": bench_ego_fb_nsource,
    "rmat_apsp": bench_rmat_apsp,
    "rmat_apsp_pipelined": bench_rmat_apsp_pipelined,
    "batch_small": bench_batch_small,
    "dense_apsp_fw": bench_dense_apsp_fw,
    "dirty_window": bench_dirty_window,
    "planner_dispatch": bench_planner_dispatch,
    "planner_tuning": bench_planner_tuning,
    "serve_queries": bench_serve_queries,
    "serve_overload": bench_serve_overload,
    "serve_fleet": bench_serve_fleet,
    "distributed_fleet": bench_distributed_fleet,
    "incremental_update": bench_incremental_update,
    "approx_apsp": bench_approx_apsp,
}


def run(
    names: list[str] | None = None,
    *,
    backend: str = "jax",
    preset: str = "mini",
    telemetry_dir: str | None = None,
    profile_dir: str | None = None,
) -> list[BenchRecord]:
    """Run the named configs. ``telemetry_dir`` (CLI ``--trace-dir``)
    turns on the flight recorder per config: each config's solvers
    record spans/events into ``<dir>/flight-<config>.jsonl`` (plus a
    Chrome trace on success and a shared ``heartbeat.json``), a
    succeeding row folds the telemetry summary into its detail, and a
    FAILED row's detail points at the flight-recorder path — the first
    artifact to read on a dead TPU pass.

    ``profile_dir`` (CLI ``--profile-store`` / ``$PJ_PROFILE_DIR``)
    turns on the cost observatory per config: every solver captures
    compiled costs + appends profile records there, rows carry their
    roofline bound in ``detail``, and each finished row is appended to
    the bench-regression history (``bench_history.jsonl``) so
    ``scripts/bench_regress.py`` can grade the next pass against it."""
    if preset not in _PRESETS:
        raise ValueError(f"preset must be one of {_PRESETS}, got {preset!r}")
    names = names or list(CONFIGS)
    unknown = [n for n in names if n not in CONFIGS]
    if unknown:
        raise ValueError(
            f"unknown config(s) {unknown}; available: {sorted(CONFIGS)}"
        )
    records = []
    profile_token = (
        _BENCH_PROFILE.set(profile_dir) if profile_dir is not None else None
    )
    for name in names:
        tel = None
        token = None
        if telemetry_dir is not None:
            from paralleljohnson_tpu.utils.telemetry import Telemetry

            tel = Telemetry.create(
                trace_dir=telemetry_dir,
                heartbeat_file=Path(telemetry_dir) / "heartbeat.json",
                label=name,
            )
            tel.progress(config=name, preset=preset, backend=backend)
            token = _BENCH_TELEMETRY.set(tel)
        t0 = time.perf_counter()
        try:
            rec = CONFIGS[name](backend, preset)
            if tel is not None:
                rec.detail["telemetry"] = tel.summary()
        except Exception as e:  # noqa: BLE001 — survive per-config death
            # A failed config writes a PARTIAL row tagged with the reason
            # instead of aborting the whole pass: every on-chip window
            # that died mid-pass so far lost the rows of the configs that
            # had already run or would have run after the crash. The
            # invariant: one row per requested config, always.
            rec = BenchRecord(
                name, backend, preset,
                time.perf_counter() - t0, 0, 0.0, 1,
                {"failed": f"{type(e).__name__}: {e}"},
            )
            if tel is not None:
                tel.event("config_failed", config=name,
                          error=type(e).__name__)
                if tel.tracer.flight_path is not None:
                    # The row is partial; the flight record has the story.
                    rec.detail["flight_recorder"] = str(
                        tel.tracer.flight_path
                    )
        finally:
            if token is not None:
                _BENCH_TELEMETRY.reset(token)
            if tel is not None:
                tel.close()
        try:
            rec.detail["platform"] = _platform()
        except Exception:  # noqa: BLE001 — a dead device must not kill the row
            rec.detail.setdefault("platform", "unknown")
        records.append(rec)
    if profile_token is not None:
        _BENCH_PROFILE.reset(profile_token)
    if profile_dir is not None:
        # Append each finished row to the bench-regression history next
        # to the profile store — the trajectory bench_regress grades the
        # next pass against. Failed rows are skipped by the normalizer
        # (a crash is not a measurement).
        try:
            from paralleljohnson_tpu.observe.regress import (
                BenchHistory,
                normalize_record,
            )

            hist = BenchHistory(profile_dir)
            for rec in records:
                for row in normalize_record(
                    json.loads(rec.as_json_line()), source="pjtpu-bench"
                ):
                    hist.append(row)
        except Exception as e:  # noqa: BLE001 — history is never fatal
            import sys

            print(f"warning: bench history append failed: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
    return records


# -- BASELINE.md maintenance -------------------------------------------------

_MARKER_BEGIN = "<!-- bench:begin -->"
_MARKER_END = "<!-- bench:end -->"


def _parse_bench_rows(text: str) -> dict[tuple[str, str, str], str]:
    """Existing bench-block rows keyed by (config, backend, preset)."""
    rows: dict[tuple[str, str, str], str] = {}
    if _MARKER_BEGIN not in text or _MARKER_END not in text:
        return rows
    block = text.split(_MARKER_BEGIN, 1)[1].split(_MARKER_END, 1)[0]
    for line in block.strip().splitlines():
        cells = [c.strip() for c in line.strip().strip("|").split("|")]
        if len(cells) >= 3 and cells[0] not in ("config", "---"):
            rows[(cells[0], cells[1], cells[2])] = line.rstrip()
    return rows


def update_baseline_md(records: list[BenchRecord], path: str) -> None:
    """Rewrite the measured-numbers block (between the bench markers) of
    BASELINE.md, merging with existing rows: newest run wins per
    (config, backend, preset), other rows are preserved."""
    from pathlib import Path

    p = Path(path)
    text = p.read_text() if p.exists() else "# BASELINE\n"
    rows = _parse_bench_rows(text)
    for r in records:
        if "failed" in r.detail and (r.config, r.backend, r.preset) in rows:
            # A failure marker must never clobber a real measurement —
            # the JSON stream records the failure; the baseline table
            # keeps the last good number.
            continue
        per_chip = r.edges_relaxed_per_sec / max(r.n_chips, 1)
        rows[(r.config, r.backend, r.preset)] = (
            f"| {r.config} | {r.backend} | {r.preset} | {r.wall_s:.3f} "
            f"| {r.edges_relaxed:,} | {per_chip:,.0f} "
            f"| {json.dumps(r.detail, sort_keys=True)} |"
        )
    lines = [
        "| config | backend | preset | wall s | edges relaxed | edges/s/chip | detail |",
        "|---|---|---|---|---|---|---|",
        *(rows[k] for k in sorted(rows)),
    ]
    block = f"{_MARKER_BEGIN}\n" + "\n".join(lines) + f"\n{_MARKER_END}"
    if _MARKER_BEGIN in text and _MARKER_END in text:
        head, rest = text.split(_MARKER_BEGIN, 1)
        _, tail = rest.split(_MARKER_END, 1)
        text = head + block + tail
    else:
        text = text.rstrip() + "\n\n## Measured results (ours)\n\n" + block + "\n"
    p.write_text(text)
