"""Command-line interface (SURVEY.md §2 #15).

Subcommands mirror the solver API and the attested benchmark configs:

  pjtpu solve  <graphspec> [--backend jax] [--sources 0,5,9 | --num-sources K]
  pjtpu sssp   <graphspec> --source S
  pjtpu batch  <n> <nodes> <p>          # many-small-graphs mode
  pjtpu info                            # devices / backends / loaders

Graph specs are anything ``load_graph`` accepts: a path (.gr/.txt) or a
scheme spec like ``er:n=1000,p=0.01`` / ``rmat:scale=20``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np


def _add_common(p: argparse.ArgumentParser) -> None:
    p.add_argument("--backend", default="jax", help="execution backend")
    p.add_argument("--precision", default="f32", choices=["f32", "f64"])
    p.add_argument("--batch-size", type=int, default=None,
                   help="sources per device batch")
    p.add_argument("--max-iterations", type=int, default=None)
    p.add_argument("--dense-threshold", type=int, default=1024)
    p.add_argument("--use-pallas", default="auto",
                   choices=["auto", "true", "false"],
                   help="Pallas kernels: auto = measured winner (currently "
                        "XLA everywhere: the dense tile kernel measured "
                        "slower on-chip; the VMEM-resident fan-out sweep "
                        "is pending on-chip numbers), true = force Pallas "
                        "(dense min-plus AND the single-device "
                        "vertex-major fan-out; interpret-mode off-TPU), "
                        "false = XLA")
    p.add_argument("--mesh-shape", default=None, metavar="N[,M...]",
                   help="devices along the sources mesh axis (e.g. 8); "
                        "default: all visible devices")
    p.add_argument("--fanout-layout", default="auto",
                   choices=["auto", "source_major", "vertex_major"],
                   help="sparse fan-out data layout (auto = vertex_major, "
                        "the measured winner)")
    p.add_argument("--frontier", default="auto",
                   choices=["auto", "true", "false"],
                   help="frontier-compacted Bellman-Ford for high-diameter "
                        "graphs: auto (low-degree graphs) / force / off")
    p.add_argument("--edge-shard", default="auto",
                   choices=["auto", "true", "false"],
                   help="shard the edge list across the mesh for "
                        "single-source Bellman-Ford (auto: mesh >1 device "
                        "AND the frontier path is not active — frontier "
                        "wins on low-degree graphs; true forces)")
    p.add_argument("--gauss-seidel", default="auto",
                   choices=["auto", "true", "false"],
                   help="blocked Gauss-Seidel for high-diameter graphs "
                        "(auto: low-degree graphs on TPU; rounds ~ path "
                        "direction changes, not diameter)")
    p.add_argument("--dia", default="auto",
                   choices=["auto", "true", "false"],
                   help="gather-free DIA stencil route for B=1 solves on "
                        "diagonally-labeled graphs (lattices/banded "
                        "meshes; auto: on TPU when the labeling qualifies)")
    p.add_argument("--dia-max-offsets", type=int, default=16,
                   help="max distinct edge diagonals the DIA route accepts")
    p.add_argument("--bucket", default="auto",
                   choices=["auto", "true", "false"],
                   help="bucketed delta-stepping route for B=1 solves on "
                        "irregular high-diameter graphs (auto: on TPU for "
                        "the low-degree family when DIA disqualifies)")
    p.add_argument("--delta", type=float, default=None,
                   help="bucket width of the bucket route (default: "
                        "auto-tune from mean edge weight x degree)")
    p.add_argument("--fw", default="auto",
                   choices=["auto", "true", "false"],
                   help="blocked min-plus Floyd-Warshall dense-APSP route "
                        "(R-Kleene tiles on the MXU; auto: squaring-regime "
                        "dense graphs where the exact MAC counters beat "
                        "min-plus squaring — ~log2(V) less work)")
    p.add_argument("--fw-threshold", type=int, default=1 << 14,
                   help="max V the blocked-FW dense route accepts "
                        "(a [V, V] f32 closure is 1 GB at 2^14)")
    p.add_argument("--fw-tile", type=int, default=None,
                   help="FW tile edge (multiple of 128; 512 default — the "
                        "first 128-multiple whose t/8 flop/byte trailing "
                        "intensity clears the TPU roofline ridge)")
    p.add_argument("--partitioned", default="auto",
                   choices=["auto", "true", "false"],
                   help="condense-solve-expand partitioned APSP (exact: "
                        "pivot partition, blocked-FW dense core, min-plus "
                        "expansion per partition; auto: TPU full-APSP on "
                        "sparse graphs in the FW size range)")
    p.add_argument("--partition-parts", type=int, default=None,
                   help="partition count of the condensed route "
                        "(default: auto-size from V)")
    p.add_argument("--dirty-window", default="auto",
                   choices=["auto", "true", "false"],
                   help="dirty-window compacted relaxation (README "
                        "'Dirty-window compaction'): per-destination-"
                        "block activity bitmaps gate the fan-out's "
                        "relaxation work — only dirty blocks' out-edge "
                        "tiles relax each round, bitwise-identical "
                        "distances, route tag vm-blocked+dw (gs+dw for "
                        "the Gauss-Seidel outer rounds). auto engages "
                        "ONLY when the profile store's trajectory "
                        "record for this graph shape shows a "
                        "collapsing frontier (never blindly)")
    p.add_argument("--planner", default="auto",
                   choices=["auto", "true", "false"],
                   help="priced dispatch registry (README 'Self-driving "
                        "dispatch'): auto/true promote a cheaper "
                        "qualified plan above the priority incumbent "
                        "when the profile store's CostModel prices BOTH "
                        "beyond the noise band (forced route flags "
                        "always win); false = pure declared priority "
                        "(the pre-registry ladder order)")
    # Certified approximate tier (ISSUE 17, README "Certified
    # approximate tier"): the budgeted hopset+bf route and its knobs.
    p.add_argument("--hopset", default="auto",
                   choices=["auto", "true", "false"],
                   help="certified (1+eps) hopset route hopset+bf: auto "
                        "qualifies it exactly when --error-budget > 0 on "
                        "a negative-free graph (budget 0 ALWAYS solves "
                        "exactly), true forces it (still requires a "
                        "positive budget — fails loud), false disables")
    p.add_argument("--error-budget", type=float, default=0.0,
                   metavar="R",
                   help="per-solve relative error budget (>= 0): the "
                        "planner admits hopset+bf only when its "
                        "certified bound can fit the budget; 0 = exact "
                        "only (default 0)")
    p.add_argument("--approx-epsilon", type=float, default=0.1,
                   metavar="E",
                   help="hopset tier target relative error eps > 0 "
                        "(drives the hop budget beta ~ log2(V)/eps; "
                        "default 0.1)")
    p.add_argument("--approx-beta", type=int, default=None, metavar="B",
                   help="explicit hop budget for hopset construction "
                        "and queries (default: auto from V and eps)")
    p.add_argument("--dw-block", type=int, default=None,
                   help="vertices per dirty-window activity bit "
                        "(default: the measured-best fine granularity)")
    p.add_argument("--gs-block-size", type=int, default=8192,
                   help="vertices per Gauss-Seidel block")
    p.add_argument("--gs-inner-cap", type=int, default=64,
                   help="max Gauss-Seidel inner iterations per block "
                        "visit (bounds extra propagation, not correctness)")
    p.add_argument("--convergence", default="auto",
                   choices=["auto", "true", "false"],
                   help="per-iteration convergence trajectory recording "
                        "(README 'Convergence observatory'): frontier "
                        "size / relaxations / residual mass per "
                        "while_loop iteration, carried on device, one "
                        "D2H after convergence — surfaces SolverStats"
                        ".convergence, heartbeat iter/frontier_size/"
                        "eta_s, 'trajectory' flight events, and profile-"
                        "store records. auto = on exactly when a "
                        "telemetry sink or profile store is configured "
                        "(otherwise the original uninstrumented kernels "
                        "compile — identical jaxpr)")
    p.add_argument("--checkpoint-dir", default=None)
    p.add_argument("--pipeline-depth", type=int, default=None,
                   help="max fan-out batches in flight (double-buffered "
                        "pipeline: batch k's row download + checkpoint "
                        "write run behind batch k+1's device compute; "
                        "each extra slot carries one more [B, V] block "
                        "in device memory); 1 = strictly serial; "
                        "default auto = profile-tuned per (platform, "
                        "shape bucket), else 2")
    p.add_argument("--compilation-cache-dir", default=None, metavar="DIR",
                   help="persistent JAX compilation cache directory so "
                        "re-runs skip Mosaic/XLA compiles (default: "
                        "$PJ_COMPILE_CACHE if set, else off)")
    p.add_argument("--retry-attempts", type=int, default=3,
                   help="max attempts per solve stage before the failure "
                        "propagates (1 disables retries)")
    p.add_argument("--stage-deadline", type=float, default=None,
                   metavar="SECONDS",
                   help="per-attempt wall-clock cap enforced by a watchdog "
                        "thread: a hung device call is logged-and-"
                        "abandoned, then retried (default: no watchdog)")
    p.add_argument("--min-source-batch", type=int, default=8,
                   help="floor of the OOM degradation schedule (the "
                        "fan-out batch is halved on RESOURCE_EXHAUSTED "
                        "down to this size, then the OOM propagates)")
    p.add_argument("--predecessors", action="store_true",
                   help="also compute shortest-path trees (saved to --output)")
    p.add_argument("--pred-extraction", default="auto",
                   choices=["auto", "true", "false"],
                   help="post-fixpoint tight-edge predecessor extraction: "
                        "--predecessors solves run the same fast auto "
                        "route as plain solves plus one extraction pass "
                        "(route tag '<route>+pred'); false = legacy "
                        "argmin sweep (route tag 'pred-sweep')")
    p.add_argument("--validate", action="store_true",
                   help="cross-check against the scipy oracle (slow)")
    p.add_argument("--output", default=None, help="write result .npz here")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit one machine-readable JSON line")
    p.add_argument("--profile", default=None, metavar="DIR",
                   help="write a jax.profiler (Perfetto/XProf) trace here")
    p.add_argument("--log-stats", action="store_true",
                   help="emit a structured JSON stats line to stderr")
    _add_observability(p)


def _add_observability(p: argparse.ArgumentParser) -> None:
    """Flight-recorder telemetry flags (README "Observability"). Defaults
    come from PJ_TRACE_DIR / PJ_HEARTBEAT_FILE / PJ_HEARTBEAT_INTERVAL /
    PJ_METRICS_FILE so the TPU pass scripts can turn telemetry on for
    every stage with four exports instead of editing every command."""
    p.add_argument("--trace-dir", default=os.environ.get("PJ_TRACE_DIR"),
                   metavar="DIR",
                   help="flight-recorder directory: incremental span/event "
                        "JSONL (readable even after a killed worker) plus "
                        "a Perfetto-loadable Chrome trace on completion "
                        "(default: $PJ_TRACE_DIR if set, else off)")
    p.add_argument("--heartbeat-file",
                   default=os.environ.get("PJ_HEARTBEAT_FILE"),
                   metavar="JSON",
                   help="atomically rewrite this progress JSON every "
                        "--heartbeat-interval seconds (stage/batch/attempt, "
                        "batches done, host RSS, device HBM in-use); a "
                        "stale mtime means hung, a fresh one progressing "
                        "(default: $PJ_HEARTBEAT_FILE if set, else off)")
    p.add_argument("--heartbeat-interval", type=float,
                   default=float(os.environ.get("PJ_HEARTBEAT_INTERVAL",
                                                "5.0")),
                   metavar="SECONDS",
                   help="heartbeat rewrite period (default: "
                        "$PJ_HEARTBEAT_INTERVAL or 5)")
    p.add_argument("--metrics-file",
                   default=os.environ.get("PJ_METRICS_FILE"),
                   metavar="PROM",
                   help="write the solve's stats as a Prometheus textfile "
                        "(pjtpu_edges_relaxed_total, pjtpu_solve_seconds, "
                        "pjtpu_retries_total, pjtpu_route_predicted_s, "
                        "pjtpu_roofline_bound{kind=...}, ...) for "
                        "scrape-based monitoring (default: "
                        "$PJ_METRICS_FILE if set)")
    p.add_argument("--trace-sample", type=float,
                   default=(float(os.environ["PJ_TRACE_SAMPLE"])
                            if os.environ.get("PJ_TRACE_SAMPLE") else None),
                   metavar="RATE",
                   help="head-based request-trace sampling rate in [0, 1] "
                        "(ISSUE 20, serve/router modes): the FIRST ingress "
                        "mints a trace_id and decides once, deterministically "
                        "(sha256 of the id), whether the whole request chain "
                        "is recorded; downstream hops honor the wire verdict "
                        "(default: $PJ_TRACE_SAMPLE; else 1.0 when "
                        "--trace-dir is set, 0 otherwise)")
    p.add_argument("--profile-store",
                   default=os.environ.get("PJ_PROFILE_DIR"),
                   metavar="DIR",
                   help="cost-observatory profile store (README 'Cost "
                        "observatory'): harvest XLA compiled costs per "
                        "route, roofline-classify the solve, and append "
                        "one record per solve to DIR/profiles.jsonl — "
                        "the calibration cli info / bench_regress / the "
                        "planned dispatch registry read (default: "
                        "$PJ_PROFILE_DIR if set, else off)")


def _telemetry(args, label: str):
    """Build the Telemetry façade the flags describe (None when off)."""
    from paralleljohnson_tpu.utils.telemetry import Telemetry

    return Telemetry.create(
        trace_dir=args.trace_dir,
        heartbeat_file=args.heartbeat_file,
        heartbeat_interval_s=args.heartbeat_interval,
        label=label,
    )


def _config(args) -> "SolverConfig":
    from paralleljohnson_tpu.config import SolverConfig

    tristate = {"auto": "auto", "true": True, "false": False}
    mesh_shape = None
    if args.mesh_shape is not None:
        mesh_shape = tuple(int(n) for n in args.mesh_shape.split(","))
    return SolverConfig(
        backend=args.backend,
        precision=args.precision,
        source_batch_size=args.batch_size,
        mesh_shape=mesh_shape,
        max_iterations=args.max_iterations,
        dense_threshold=args.dense_threshold,
        use_pallas=tristate[args.use_pallas],
        fanout_layout=args.fanout_layout,
        frontier=tristate[args.frontier],
        edge_shard=tristate[args.edge_shard],
        gauss_seidel=tristate[args.gauss_seidel],
        dia=tristate[args.dia],
        dia_max_offsets=args.dia_max_offsets,
        bucket=tristate[args.bucket],
        delta=args.delta,
        fw=tristate[args.fw],
        fw_threshold=args.fw_threshold,
        fw_tile=args.fw_tile,
        partitioned=tristate[args.partitioned],
        partition_parts=args.partition_parts,
        dirty_window=tristate[args.dirty_window],
        dw_block=args.dw_block,
        gs_block_size=args.gs_block_size,
        gs_inner_cap=args.gs_inner_cap,
        pred_extraction=tristate[args.pred_extraction],
        checkpoint_dir=args.checkpoint_dir,
        pipeline_depth=args.pipeline_depth,
        compilation_cache_dir=args.compilation_cache_dir,
        validate=args.validate,
        retry_attempts=args.retry_attempts,
        stage_deadline_s=args.stage_deadline,
        min_source_batch=args.min_source_batch,
        planner=tristate[args.planner],
        hopset=tristate[args.hopset],
        approx_epsilon=args.approx_epsilon,
        approx_beta=args.approx_beta,
        error_budget=args.error_budget,
        profile_store=args.profile_store,
        convergence=tristate[args.convergence],
        telemetry=_telemetry(args, args.command),
    )


def _write_metrics(stats, args) -> None:
    if getattr(args, "metrics_file", None):
        from paralleljohnson_tpu.utils.telemetry import write_prom_metrics

        write_prom_metrics(stats, args.metrics_file,
                           labels={"command": args.command})


def _report_approx(res, args) -> None:
    """Report an ApproxResult (route hopset+bf): the certified-bound
    summary instead of the exact SolverStats surface."""
    fin = np.isfinite(res.max_error)
    payload = {
        "shape": list(res.dist.shape),
        "route": res.route,
        "exact": bool(res.exact),
        "certified_frac": round(float(np.mean(fin)), 6),
        "certified_max_bound": (
            float(res.max_error[fin].max()) if bool(fin.any()) else 0.0
        ),
        **res.stats,
        "plan": res.plan,
    }
    if args.output:
        np.savez_compressed(args.output, dist=res.dist,
                            sources=res.sources,
                            max_error=res.max_error)
        payload["output"] = args.output
    if args.as_json:
        print(json.dumps(payload))
    else:
        print(f"distances: {res.dist.shape}, route {res.route} "
              f"(eps {res.stats.get('epsilon'):g}, beta "
              f"{res.stats.get('beta')}, "
              f"{payload['certified_frac']:.1%} certified, max bound "
              f"{payload['certified_max_bound']:g})")
        print(f"  construction: {res.stats.get('construction_s', 0) * 1e3:9.2f} ms"
              f"  query: {res.stats.get('query_s', 0) * 1e3:9.2f} ms")
        if res.plan:
            print(f"  planner: chose {res.plan.get('chosen')} — "
                  f"{res.plan.get('reason')}")


def _report(res, args) -> None:
    _write_metrics(res.stats, args)
    if getattr(args, "log_stats", False):
        from paralleljohnson_tpu.utils.profiling import log_stats

        log_stats(res.stats, label=args.command)
    # Device-aware reduction: np.isfinite on a device-resident dist would
    # download the whole matrix just to print one fraction.
    from paralleljohnson_tpu.utils.reductions import finite_frac

    finite = finite_frac(res.dist)
    payload = {
        "shape": list(res.dist.shape),
        "finite_fraction": round(finite, 6),
        **res.stats.as_dict(),
    }
    if args.output:
        arrays = dict(dist=res.dist, sources=res.sources,
                      potentials=res.potentials)
        if res.predecessors is not None:
            arrays["predecessors"] = res.predecessors
        np.savez_compressed(args.output, **arrays)
        payload["output"] = args.output
    if args.as_json:
        print(json.dumps(payload))
    else:
        print(f"distances: {res.dist.shape}, {finite:.1%} finite")
        for phase, secs in res.stats.phase_seconds.items():
            print(f"  {phase:>14s}: {secs * 1e3:9.2f} ms")
        print(f"  edges relaxed: {res.stats.edges_relaxed:,} "
              f"({res.stats.edges_relaxed_per_second():,.0f}/s)")
        # Resilience summary — only when a recovery path actually fired
        # (a clean solve stays clean; a degraded one must say so).
        s = res.stats
        if s.retries or s.oom_degradations or s.abandoned_stages:
            parts = []
            if s.retries:
                parts.append(f"{s.retries} retries")
            if s.oom_degradations:
                parts.append(
                    f"{s.oom_degradations} OOM degradations "
                    f"(final batch {s.final_batch})"
                )
            if s.abandoned_stages:
                parts.append(
                    f"abandoned: {', '.join(s.abandoned_stages)}"
                )
            print(f"  resilience: {'; '.join(parts)}")
        if s.batches_resumed:
            print(f"  batches resumed from checkpoint: {s.batches_resumed}")
        # Roofline line (cost observatory) — only when the solve was
        # actually attributable (analytic capture or dominant host IO);
        # an unknown bound would just be noise on every plain solve.
        roof = getattr(s, "roofline", None)
        if roof and roof.get("bound") not in (None, "unknown"):
            line = f"  roofline: {roof['bound']}-bound"
            if roof.get("why"):
                line += f" ({roof['why']})"
            print(line)
            if s.predicted_s is not None:
                print(
                    f"  cost model: predicted {s.predicted_s * 1e3:.2f} ms"
                    f" vs measured {s.compute_seconds * 1e3:.2f} ms compute"
                )
        # Planner decision (ISSUE 14): chosen plan + why-line, and
        # any profile-tuned parameters the solve resolved.
        plan = getattr(s, "plan", None)
        if plan:
            line = f"  plan: {plan.get('built') or plan.get('chosen')}"
            if plan.get("degraded"):
                line += f" (degraded from {plan.get('chosen')})"
            if plan.get("reason"):
                line += f" — {plan['reason']}"
            print(line)
            params = plan.get("params") or {}
            shown = {k: v for k, v in params.items()
                     if not k.endswith("_source")}
            if shown:
                print("  plan params: " + ", ".join(
                    f"{k}={v}" for k, v in sorted(shown.items())
                ))
        # Convergence-observatory summary (ISSUE 9) — one line per
        # instrumented phase when the trajectory was recorded (off by
        # default; a plain solve stays quiet).
        conv = getattr(s, "convergence", None)
        if conv:
            for phase, c in conv.items():
                print(
                    f"  convergence[{phase}]: {c.get('iterations', 0)} "
                    f"iter (half-life {c.get('frontier_half_life', 0)}), "
                    f"tail {c.get('tail_fraction', 0.0):.0%}, "
                    "JFR-skippable "
                    f"{c.get('jfr_skippable_edge_frac', 0.0):.0%} of "
                    "examined edges"
                )
        # Pipeline summary — only when the fan-out actually staged work
        # off the critical path (a serial solve stays quiet).
        if s.download_s or s.ckpt_wait_s or s.overlap_saved_s:
            print(
                f"  pipeline (depth {s.final_pipeline_depth}): "
                f"download {s.download_s * 1e3:.2f} ms, "
                f"ckpt wait {s.ckpt_wait_s * 1e3:.2f} ms, "
                f"overlap saved {s.overlap_saved_s * 1e3:.2f} ms"
            )
        if args.output:
            print(f"  wrote {args.output}")


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="pjtpu",
        description="TPU-native parallel Johnson's-algorithm APSP solver",
    )
    sub = parser.add_subparsers(dest="command", required=True)

    p_solve = sub.add_parser("solve", help="Johnson APSP (all or some sources)")
    p_solve.add_argument("graph", help="path or loader spec")
    p_solve.add_argument("--sources", default=None,
                         help="comma-separated source vertices (default: all)")
    p_solve.add_argument("--num-sources", type=int, default=None,
                         help="solve the first K sources only")
    p_solve.add_argument("--reduce", default=None, metavar="REDUCER",
                         choices=["checksum", "eccentricity", "reach_count"],
                         help="streaming mode: reduce each source batch's "
                              "rows on device instead of materializing the "
                              "distance matrix (RMAT-22-scale solves)")
    _add_common(p_solve)

    p_sssp = sub.add_parser("sssp", help="single-source Bellman-Ford")
    p_sssp.add_argument("graph")
    p_sssp.add_argument("--source", type=int, required=True)
    _add_common(p_sssp)

    p_batch = sub.add_parser("batch", help="many-small-graphs vmapped APSP")
    p_batch.add_argument("count", type=int)
    p_batch.add_argument("nodes", type=int)
    p_batch.add_argument("p", type=float)
    p_batch.add_argument("--seed", type=int, default=0)
    _add_common(p_batch)

    p_bench = sub.add_parser("bench", help="attested benchmark configs")
    p_bench.add_argument("configs", nargs="*",
                         help="subset of configs (default: all)")
    p_bench.add_argument("--backend", default="jax")
    p_bench.add_argument("--preset", default="mini",
                         choices=["smoke", "mini", "full"])
    p_bench.add_argument("--update-baseline", default=None, metavar="MD",
                         help="rewrite the measured table in this BASELINE.md")
    p_bench.add_argument("--trace-dir",
                         default=os.environ.get("PJ_TRACE_DIR"), metavar="DIR",
                         help="per-config flight recorder: span/event JSONL "
                              "+ Chrome trace + heartbeat.json under DIR; "
                              "failed rows reference their flight file "
                              "(default: $PJ_TRACE_DIR if set, else off)")
    p_bench.add_argument("--profile-store",
                         default=os.environ.get("PJ_PROFILE_DIR"),
                         metavar="DIR",
                         help="cost-observatory profile store: every "
                              "config's solves capture compiled costs + "
                              "append profile records there, rows fold "
                              "their roofline bound into detail, and the "
                              "pass appends its rows to the bench-"
                              "regression history (default: "
                              "$PJ_PROFILE_DIR if set, else off)")

    p_serve = sub.add_parser(
        "serve",
        help="query-serving request loop over a tile store: JSONL "
             "queries in (stdin or --queries), one JSON answer line "
             "per query out (README 'Query serving')",
    )
    p_serve.add_argument("graph", nargs="?", default=None,
                         help="path or loader spec (omit with --route: "
                              "the router serves from the fleet's "
                              "replicas, not a graph of its own)")
    p_serve.add_argument("--store-dir", default=None, metavar="DIR",
                         help="solve/checkpoint directory the tile store "
                              "attaches to (finished or in-progress; "
                              "scheduled batches persist back into it); "
                              "absent = in-memory hot/warm tiers only")
    p_serve.add_argument("--queries", default="-", metavar="JSONL",
                         help="query file, '-' = stdin (default). One "
                              "JSON object per line: {\"id\": ..., "
                              "\"source\": S, \"dst\": T | [T,...] | null, "
                              "\"mode\": \"exact\"|\"approx\"}")
    p_serve.add_argument("--landmarks", type=int, default=0, metavar="K",
                         help="build (or reuse, when persisted in the "
                              "store) a K-pivot landmark index for "
                              "bounded-error approximate answers "
                              "(default: 0 = none; --miss-policy "
                              "landmark implies 16)")
    p_serve.add_argument("--miss-policy", default="solve",
                         choices=["solve", "landmark", "hopset"],
                         help="store miss on an unsolved source: "
                              "'solve' schedules one exact batch "
                              "through the resilient solver; 'landmark' "
                              "answers immediately with (estimate, "
                              "max_error) bounds; 'hopset' answers with "
                              "the (1+eps) hopset tier's certified "
                              "bounds (implies building/loading a "
                              "hopset; composes with the landmark "
                              "interval when one is attached — the "
                              "tighter certified bound wins)")
    p_serve.add_argument("--hot-rows", type=int, default=None,
                         help="hot-tier capacity in rows (device-"
                              "resident; default 128)")
    p_serve.add_argument("--warm-rows", type=int, default=None,
                         help="warm-tier host-RAM LRU capacity in rows "
                              "(default 4096)")
    p_serve.add_argument("--batch-queries", type=int, default=64,
                         help="aggregate up to this many request lines "
                              "into one source-batched lookup")
    # Device-resident lookups (ISSUE 16, README "Serving queries"):
    # the planner prices host tier walk vs device megabatch per batch.
    p_serve.add_argument("--device-lookup", default="auto",
                         choices=["auto", "on", "off"],
                         help="lookup path dispatch: 'auto' lets the "
                              "planner price host tier walk vs device "
                              "megabatch per batch (bit-for-bit "
                              "identical answers), 'on'/'off' force "
                              "one path (default: auto)")
    p_serve.add_argument("--landmark-picker", default="uniform",
                         choices=["uniform", "coverage", "boundary"],
                         help="pivot picker for a freshly built "
                              "landmark index or hopset: 'coverage' "
                              "weights candidates by degree (hub "
                              "coverage), 'boundary' samples partition-"
                              "frontier vertices (corridor/mesh "
                              "graphs), 'uniform' is the reproducible "
                              "default")
    p_serve.add_argument("--batch-window", type=int, default=None,
                         metavar="W",
                         help="micro-batch up to W concurrent socket "
                              "requests into one engine batch "
                              "(--listen only; default 32; 1 disables)")
    p_serve.add_argument("--batch-wait-ms", type=float, default=None,
                         metavar="MS",
                         help="optional fixed window the micro-batch "
                              "leader waits to accumulate followers "
                              "(default 0: width comes only from the "
                              "convoy — no idle-server latency tax)")
    p_serve.add_argument("--summary", action="store_true",
                         help="print the serving summary JSON (engine + "
                              "store counters, hit rate) to stderr at exit")
    p_serve.add_argument("--slo-p99-ms", type=float, default=250.0,
                         help="serving SLO latency target: p99 of the "
                              "streaming latency histogram must stay "
                              "under this (default 250 ms)")
    p_serve.add_argument("--slo-availability", type=float, default=0.999,
                         help="serving SLO availability target: the "
                              "good-query fraction whose complement is "
                              "the error budget burn-rate alerts spend "
                              "(default 0.999)")
    p_serve.add_argument("--stats-interval", type=float, default=5.0,
                         metavar="SECONDS",
                         help="atomically rewrite serve_stats.json in the "
                              "store dir every N seconds while serving "
                              "(heartbeat idiom — a killed process leaves "
                              "stats fresh to within N; 0 disables)")
    # Traffic front end (ISSUE 15, README "Traffic front end"): socket
    # serving with designed overload behavior instead of the stdin loop.
    p_serve.add_argument("--listen", default=None, metavar="HOST:PORT",
                         help="serve newline-delimited JSON over TCP "
                              "instead of the stdin/--queries loop: one "
                              "protocol header per connection, per-"
                              "connection worker threads over one shared "
                              "engine, admission control + certified load "
                              "shedding + SIGTERM drain; port 0 picks an "
                              "ephemeral port (announced on stdout)")
    p_serve.add_argument("--max-connections", type=int, default=64,
                         help="connection-admission bound: past it a new "
                              "connection gets one {\"error\": "
                              "\"overloaded\", \"retry_after_ms\": ...} "
                              "line and a close (default 64)")
    p_serve.add_argument("--max-inflight", type=int, default=8,
                         help="in-flight query bound: past it a request "
                              "is rejected (or, with deadline_ms, waits "
                              "up to its own deadline for a slot) "
                              "instead of queueing unboundedly (default 8)")
    p_serve.add_argument("--shed-policy", default="landmark",
                         choices=["landmark", "hopset", "priced",
                                  "reject", "off"],
                         help="overload shedding when the SLO burn alert "
                              "fires: 'landmark'/'hopset' downgrade "
                              "exact-MISS queries to that certified "
                              "tier's flagged {shed: true, exact: "
                              "false, max_error: ...} answers (hits "
                              "still answer exactly; each implies its "
                              "index), 'priced' orders the two "
                              "certified tiers by predicted per-query "
                              "cost and rejects only when neither "
                              "exists, 'reject' turns misses into "
                              "overloaded rejections, 'off' never "
                              "sheds (default landmark)")
    p_serve.add_argument("--drain-timeout", type=float, default=10.0,
                         metavar="SECONDS",
                         help="SIGTERM drain deadline: stop accepting, "
                              "finish in-flight requests up to this "
                              "long, force-close stragglers, flush "
                              "snapshots, exit 0 (default 10)")
    p_serve.add_argument("--retry-after-ms", type=int, default=100,
                         help="the retry_after_ms hint carried by "
                              "overloaded rejections (default 100)")
    p_serve.add_argument("--shed-min-events", type=int, default=20,
                         help="low-traffic guard: shedding engages only "
                              "when the burn verdict is backed by at "
                              "least this many observations inside the "
                              "burn rule's long window — one rejection "
                              "on a near-idle server must not degrade "
                              "the next answer (default 20; 0 disables "
                              "the guard)")
    # Replicated serve fleet (ISSUE 18, README "Replicated serve
    # fleet"): replicas heartbeat-register into a fleet dir; a thin
    # router mode consistent-hashes sources to the owning replica.
    p_serve.add_argument("--max-inflight-per-client", type=int,
                         default=None, metavar="N",
                         help="per-client fairness cap UNDER "
                              "--max-inflight: a client (request "
                              "client_id, else peer address) past N "
                              "in-flight gets {\"error\": \"overloaded\", "
                              "\"client_limited\": true} while other "
                              "clients keep flowing (default: off)")
    p_serve.add_argument("--http", action="store_true",
                         help="speak minimal HTTP/1.1 instead of "
                              "newline-delimited JSON on --listen: POST "
                              "/query (body = one protocol line, same "
                              "answer doc back), GET /healthz (200/503 "
                              "by solve-heartbeat freshness); overload "
                              "maps to 429 + Retry-After")
    p_serve.add_argument("--fleet-dir", default=None, metavar="DIR",
                         help="register this replica in a serve-fleet "
                              "directory: an atomically-heartbeated "
                              "membership record under serve/replicas/ "
                              "(stale-by-age = ejected from routing); "
                              "requires --listen")
    p_serve.add_argument("--replica-id", default=None, metavar="ID",
                         help="membership record name under --fleet-dir "
                              "(default: replica-<pid>)")
    p_serve.add_argument("--replica-heartbeat", type=float, default=1.0,
                         metavar="SECONDS",
                         help="membership heartbeat interval (default 1; "
                              "readers eject records stale by several "
                              "intervals)")
    p_serve.add_argument("--route", default=None, metavar="FLEET_DIR",
                         help="router mode: forward pjtpu-serve/1 lines "
                              "to the owning replica of FLEET_DIR's "
                              "consistent-hash table (published "
                              "atomically as serve/routing.json with a "
                              "monotonic epoch); on replica death "
                              "(stale heartbeat or connection refused) "
                              "re-publishes the table minus the corpse "
                              "and retries — bounded attempts, then an "
                              "explicit unavailable error. Uses "
                              "--listen for the bind address")
    p_serve.add_argument("--replica-stale", type=float, default=None,
                         metavar="SECONDS",
                         help="router/top: eject replicas whose "
                              "membership record is older than this "
                              "(default: 5)")
    p_serve.add_argument("--tune-dir", default=None, metavar="DIR",
                         help="idle-capacity tuning (ISSUE 19): while "
                              "the replica has no open connections it "
                              "drains probe leases, one at a time, from "
                              "this tuning-fleet directory (planned by "
                              "pjtpu tune --fleet-dir); serving traffic "
                              "always preempts the next claim")
    _add_common(p_serve)

    p_top = sub.add_parser(
        "top",
        help="fleet-wide operations console (README 'Live operations'): "
             "join serve snapshots, coordinator lease table, worker "
             "heartbeats + live metrics, and repair status into one "
             "live-refreshing view (or --once [--json] for scripts/CI)",
    )
    p_top.add_argument("--serve-store", default=None, metavar="DIR",
                       help="serving store / checkpoint directory whose "
                            "graph_* subdirectories' serve_stats.json + "
                            "repair_status.json to join")
    p_top.add_argument("--coordinator-dir", default=None, metavar="DIR",
                       help="fleet coordinator directory (lease table, "
                            "worker heartbeats, metrics/<worker>.json)")
    p_top.add_argument("--fleet-dir", default=None, metavar="DIR",
                       help="serve-fleet directory (serve/replicas/*.json "
                            "membership heartbeats + routing.json): merge "
                            "per-replica histograms/SLO burn into one "
                            "service-level verdict with per-replica "
                            "breakdown; dead/stale replicas flagged")
    p_top.add_argument("--once", action="store_true",
                       help="print one view and exit (default: refresh "
                            "every --interval seconds until interrupted)")
    p_top.add_argument("--json", action="store_true", dest="as_json",
                       help="emit the joined document as JSON (one line "
                            "with --once, one line per refresh otherwise)")
    p_top.add_argument("--interval", type=float, default=2.0,
                       metavar="SECONDS",
                       help="refresh period of the live view (default 2)")
    p_top.add_argument("--stale-after", type=float, default=15.0,
                       metavar="SECONDS",
                       help="flag a snapshot/heartbeat stale once its own "
                            "publish stamp is older than this (default 15)")

    p_update = sub.add_parser(
        "update",
        help="incremental graph update (README 'Incremental updates'): "
             "apply an edge-update batch against a solved "
             "--checkpoint-dir, re-closing only dirty parts + the "
             "boundary core and re-expanding only affected source "
             "ranges; the repaired checkpoint lands under the new "
             "graph digest, bitwise-identical to a fresh full solve "
             "on integer weights",
    )
    p_update.add_argument("graph",
                          help="path or loader spec of the PRE-update "
                               "graph the checkpoint was solved from "
                               "(digests must match)")
    p_update.add_argument("--updates", required=True, metavar="FILE",
                          help="edge-update file: one update per line, "
                               "either JSON {\"u\": U, \"v\": V, \"w\": "
                               "W|null} or 'U V W' text (w of null/inf "
                               "removes the edge; last update to a pair "
                               "wins)")
    p_update.add_argument("--dry-run", action="store_true",
                          help="print the dirty-set diagnosis (which "
                               "parts / the core a repair would "
                               "re-close) without repairing")
    p_update.add_argument("--fleet-dir", default=None, metavar="DIR",
                          help="shard the row regeneration through "
                               "repair leases of a fleet coordinator "
                               "planned in DIR (in-process workers; "
                               "inspect with pjtpu fleet status)")
    p_update.add_argument("--fleet-workers", type=int, default=2,
                          help="worker claim loops for --fleet-dir "
                               "(default 2)")
    p_update.add_argument("--strategy", default="auto",
                          choices=["auto", "repair", "resolve"],
                          help="repair-vs-resolve policy (ISSUE 19): "
                               "auto prices the dirty-part repair "
                               "against a full re-solve from learned "
                               "profile records and picks the cheaper "
                               "(unpriced: repair, the old behavior); "
                               "repair/resolve force one side")
    _add_common(p_update)

    p_tune = sub.add_parser(
        "tune",
        help="self-proposing planner (README 'Self-proposing planner'): "
             "probe candidate values of every declared tunable knob "
             "under hard wall-clock budgets, landing ordinary "
             "kind='plan' records + kind='tune' audit rows in the "
             "profile store; the usual 25% noise band decides "
             "promotion. With --fleet-dir, plan a tuning-lease "
             "coordinator that idle fleet workers / serve replicas "
             "drain instead",
    )
    p_tune.add_argument("graph", help="path or loader spec of the graph "
                                      "(= the shape bucket) to calibrate")
    p_tune.add_argument("--store-dir", default=None, metavar="DIR",
                        help="profile store to land evidence in "
                             "(default: $PJ_PROFILE_DIR, else "
                             "bench_artifacts/profiles)")
    p_tune.add_argument("--knobs", default=None, metavar="K1,K2",
                        help="comma-separated knob subset (default: every "
                             "knob a registered Plan declares)")
    p_tune.add_argument("--probe-budget", type=float, default=30.0,
                        metavar="SECONDS",
                        help="hard wall-clock cap per probe solve; a "
                             "probe over the cap is censored — recorded "
                             "but never promotable (default 30)")
    p_tune.add_argument("--bucket-budget", type=float, default=120.0,
                        metavar="SECONDS",
                        help="total probe budget for this bucket; 0 "
                             "means do nothing at all (default 120)")
    p_tune.add_argument("--fleet-dir", default=None, metavar="DIR",
                        help="plan the probes as coordinator tuning "
                             "leases in DIR (lease = knob x candidate "
                             "chunk, chunk sizes priced from the cost "
                             "model) and run --workers in-process claim "
                             "loops; point solve workers/serve replicas "
                             "at DIR via --tune-dir to drain it from "
                             "idle capacity instead")
    p_tune.add_argument("--workers", type=int, default=1,
                        help="in-process claim loops for --fleet-dir "
                             "(default 1; 0 = plan only)")
    p_tune.add_argument("--harvest", action="store_true",
                        help="merge committed tuning-lease shards from "
                             "--fleet-dir into the store and exit "
                             "(idempotent)")
    p_tune.add_argument("--json", action="store_true", dest="as_json")

    p_fleet = sub.add_parser(
        "fleet",
        help="distributed solve fleet over a coordinator dir (README "
             "'Distributed fleet'): solve = plan + run N local CPU "
             "worker subprocesses + merge shard manifests; status = "
             "lease/heartbeat snapshot; resume = continue an "
             "interrupted fleet",
    )
    fsub = p_fleet.add_subparsers(dest="fleet_command", required=True)
    pf_solve = fsub.add_parser(
        "solve", help="plan a fleet, run local workers, merge the manifest"
    )
    pf_solve.add_argument("graph", help="path or loader spec (workers "
                          "re-load it and verify the content digest)")
    pf_solve.add_argument("--coordinator-dir", required=True, metavar="DIR",
                          help="the fleet's shared state dir (plan + lease "
                               "log + heartbeats + per-worker checkpoint "
                               "shards + merged manifest)")
    pf_solve.add_argument("--workers", type=int, default=2,
                          help="local CPU worker subprocesses (default 2); "
                               "pod slices run one worker per host "
                               "directly — see the module docstring of "
                               "distributed.launch")
    pf_solve.add_argument("--num-sources", type=int, default=None,
                          help="solve the first K sources only "
                               "(default: all V)")
    pf_solve.add_argument("--lease-sources", type=int, default=None,
                          help="sources per lease (default: ~4 leases "
                               "per worker)")
    pf_solve.add_argument("--lease-deadline", type=float, default=30.0,
                          metavar="SECONDS",
                          help="lease deadline; at lapse a fresh worker "
                               "heartbeat extends it, a stale one "
                               "re-queues the range (default 30)")
    pf_solve.add_argument("--heartbeat-stale", type=float, default=None,
                          metavar="SECONDS",
                          help="heartbeat age past which a worker counts "
                               "as dead (default: 2x the lease deadline)")
    pf_solve.add_argument("--backend", default="jax")
    pf_solve.add_argument("--batch-size", type=int, default=None,
                          help="worker source_batch_size override")
    pf_solve.add_argument("--in-process", action="store_true",
                          help="run the workers sequentially in this "
                               "process instead of as subprocesses "
                               "(debugging / smoke)")
    pf_status = fsub.add_parser(
        "status", help="lease counts, requeues, heartbeat ages, one JSON"
    )
    pf_status.add_argument("--coordinator-dir", required=True, metavar="DIR")
    pf_resume = fsub.add_parser(
        "resume", help="continue an interrupted fleet: re-open the "
                       "coordinator, run workers over the surviving "
                       "state (committed leases stay committed; held "
                       "leases re-queue via heartbeat staleness)"
    )
    pf_resume.add_argument("--coordinator-dir", required=True, metavar="DIR")
    pf_resume.add_argument("--workers", type=int, default=2)

    p_info = sub.add_parser(
        "info",
        help="environment / plugin summary; with a graph spec, also the "
             "per-graph kernel-route diagnosis (which route each phase "
             "would take and why)",
    )
    p_info.add_argument("graph", nargs="?", default=None,
                        help="optional loader spec / path to diagnose")
    p_info.add_argument("--serve-store", default=None, metavar="DIR",
                        help="also report a tile store's persisted "
                             "serving state (capacity, landmark count, "
                             "hit-rate counters from serve_stats.json)")
    p_info.add_argument("--profile-store", default=None, metavar="DIR",
                        help="cost-observatory profile store to price "
                             "routes from (default: $PJ_PROFILE_DIR, "
                             "else bench_artifacts/profiles when present)")
    p_info.add_argument("--updates", default=None, metavar="FILE",
                        help="with a graph spec and --checkpoint-dir: "
                             "diagnose this edge-update file's dirty set "
                             "(which parts / the core a pjtpu update "
                             "would re-close) without repairing")
    p_info.add_argument("--checkpoint-dir", default=None, metavar="DIR",
                        help="checkpoint directory for the --updates "
                             "dirty-set diagnosis")
    p_info.add_argument("--json", action="store_true", dest="as_json")

    args = parser.parse_args(argv)

    from paralleljohnson_tpu.utils.platform import honor_cpu_platform_request

    honor_cpu_platform_request()

    from paralleljohnson_tpu import (
        NegativeCycleError,
        ParallelJohnsonSolver,
        SolveCorruptionError,
        StageAbandonedError,
        available_backends,
        load_graph,
    )
    from paralleljohnson_tpu.graphs import available_loaders, random_graph_batch

    if args.command == "bench":
        from paralleljohnson_tpu import benchmarks

        records = benchmarks.run(
            args.configs or None, backend=args.backend, preset=args.preset,
            telemetry_dir=args.trace_dir, profile_dir=args.profile_store,
        )
        for r in records:
            print(r.as_json_line())
        if args.update_baseline:
            benchmarks.update_baseline_md(records, args.update_baseline)
        return 0

    if args.command == "top":
        import time as _time

        from paralleljohnson_tpu.observe.top import gather_ops, render_ops

        if (args.serve_store is None and args.coordinator_dir is None
                and args.fleet_dir is None):
            print(
                "error: pjtpu top needs --serve-store, --fleet-dir, "
                "and/or --coordinator-dir (nothing to watch)",
                file=sys.stderr,
            )
            return 1
        try:
            while True:
                doc = gather_ops(
                    serve_store=args.serve_store,
                    coordinator_dir=args.coordinator_dir,
                    serve_fleet=args.fleet_dir,
                    stale_after_s=args.stale_after,
                )
                if args.as_json:
                    print(json.dumps(doc), flush=True)
                else:
                    if not args.once:
                        # ANSI clear + home: repaint in place like top(1).
                        print("\x1b[2J\x1b[H", end="")
                    print(render_ops(doc), flush=True)
                if args.once:
                    return 0
                _time.sleep(max(0.1, args.interval))
        except KeyboardInterrupt:
            return 0

    if args.command == "fleet":
        from paralleljohnson_tpu.distributed import (
            Coordinator,
            CoordinatorError,
            launch_local_fleet,
            plan_fleet,
        )
        from paralleljohnson_tpu.distributed.launch import (
            run_in_process_fleet,
        )

        try:
            if args.fleet_command == "status":
                print(json.dumps(Coordinator(args.coordinator_dir).status(),
                                 indent=2))
                return 0
            if args.fleet_command == "solve":
                config = {}
                if args.batch_size is not None:
                    config["source_batch_size"] = args.batch_size
                coord = plan_fleet(
                    args.coordinator_dir,
                    args.graph,
                    n_workers=args.workers,
                    num_sources=args.num_sources,
                    lease_sources=args.lease_sources,
                    lease_deadline_s=args.lease_deadline,
                    heartbeat_stale_s=args.heartbeat_stale,
                    backend=args.backend,
                    config=config,
                )
            else:  # resume
                coord = Coordinator(args.coordinator_dir)
            if getattr(args, "in_process", False):
                report = run_in_process_fleet(coord, args.workers)
            else:
                report = launch_local_fleet(coord, args.workers)
            print(json.dumps(report.as_dict()))
            if not report.ok:
                print(
                    f"error: fleet incomplete — "
                    f"{report.leases_committed}/{report.leases_total} "
                    f"leases committed (resume with: pjtpu fleet resume "
                    f"--coordinator-dir {coord.dir})",
                    file=sys.stderr,
                )
                return 3
            return 0
        except CoordinatorError as e:
            print(f"error: {e}", file=sys.stderr)
            return 1

    if args.command == "tune":
        from paralleljohnson_tpu import tuner as _tuner
        from paralleljohnson_tpu.distributed.coordinator import (
            CoordinatorError as _CoordErr,
        )

        store_dir = (
            args.store_dir
            or os.environ.get("PJ_PROFILE_DIR")
            or "bench_artifacts/profiles"
        )
        knobs = (
            [k.strip() for k in args.knobs.split(",") if k.strip()]
            if args.knobs else None
        )
        try:
            if args.harvest:
                if not args.fleet_dir:
                    print("error: --harvest needs --fleet-dir",
                          file=sys.stderr)
                    return 1
                print(json.dumps(
                    _tuner.harvest_tuning(args.fleet_dir, store_dir)
                ))
                return 0
            g = load_graph(args.graph)
            if args.fleet_dir:
                coord = _tuner.plan_tuning_fleet(
                    args.fleet_dir, graph_spec=args.graph, graph=g,
                    knobs=knobs, store_dir=store_dir,
                    probe_budget_s=args.probe_budget,
                )
                out = {"fleet_dir": str(coord.dir),
                       "leases": len(coord.leases()),
                       "workers": []}
                for w in range(args.workers):
                    out["workers"].append(_tuner.run_tuning_worker(
                        args.fleet_dir, f"tuner{w}", graph=g,
                    ))
                if args.workers:
                    out["harvest"] = _tuner.harvest_tuning(
                        args.fleet_dir, store_dir
                    )
                print(json.dumps(out, default=str))
                return 0
            summary = _tuner.tune_bucket(
                g, store_dir=store_dir, knobs=knobs,
                probe_budget_s=args.probe_budget,
                bucket_budget_s=args.bucket_budget,
            )
            print(json.dumps(summary, default=str,
                             indent=None if args.as_json else 2))
            return 0
        except (_CoordErr, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 1

    if args.command == "info":
        import jax

        from paralleljohnson_tpu.config import SolverConfig as _SC

        _dc = _SC()
        _dc_heartbeat_default = float(
            os.environ.get("PJ_HEARTBEAT_INTERVAL", "5.0")
        )
        info = {
            "backends": available_backends(),
            "loaders": available_loaders(),
            "devices": [str(d) for d in jax.devices()],
            "default_backend_platform": jax.default_backend(),
            # The failure-handling defaults every solve runs under
            # (README "Failure handling"; solve/sssp report the
            # per-solve retries/oom_degradations/final_batch/
            # abandoned_stages counters in their stats output).
            "resilience": {
                "retry_attempts": _dc.retry_attempts,
                "retry_backoff_s": _dc.retry_backoff_s,
                "stage_deadline_s": _dc.stage_deadline_s,
                "min_source_batch": _dc.min_source_batch,
                "oom_degradation": (
                    "on RESOURCE_EXHAUSTED: collapse the pipeline window "
                    "to 1, then clear_caches + halve the source batch "
                    "(floor min_source_batch), resume from the failed "
                    "batch"
                ),
            },
            # The flight-recorder telemetry surface (README
            # "Observability"): what each knob produces and the offline
            # tool that reads a dead run's artifacts.
            "observability": {
                "flags": {
                    "--trace-dir": "incremental span/event JSONL "
                                   "(flight-<cmd>.jsonl, readable after a "
                                   "kill) + Perfetto trace-<cmd>.json",
                    "--heartbeat-file": "progress JSON atomically "
                                        "rewritten every interval "
                                        "(stage/batch, batches_done, host "
                                        "RSS, device HBM in-use)",
                    "--heartbeat-interval": _dc_heartbeat_default,
                    "--metrics-file": "Prometheus textfile export "
                                      "(pjtpu_* counters/gauges)",
                    "--trace-sample": "head-based request-trace sampling "
                                      "rate for serve/router ingress "
                                      "(default 1.0 when --trace-dir is "
                                      "set, 0 otherwise; the verdict "
                                      "travels the wire so downstream "
                                      "hops never re-decide)",
                },
                "env_defaults": ["PJ_TRACE_DIR", "PJ_HEARTBEAT_FILE",
                                 "PJ_HEARTBEAT_INTERVAL", "PJ_METRICS_FILE",
                                 "PJ_TRACE_SAMPLE"],
                "request_tracing": {
                    "ingress": "router or replica — whichever sees the "
                               "request first mints trace_id and samples "
                               "once; the wire context ({'trace': {'id', "
                               "'parent', 'sampled'}}) threads every hop",
                    "spans": ["route_request", "forward", "serve_request",
                              "convoy_batch", "convoy_member", "query",
                              "serve_solve", "device_megabatch",
                              "shed_decision"],
                    "assembler": "python scripts/trace_assemble.py "
                                 "DIR... [--perfetto-dir OUT] [--check]",
                    "request_tree": "python scripts/trace_summary.py "
                                    "--request TRACE_ID DIR...",
                },
                "offline_reader": "python scripts/trace_summary.py "
                                  "<flight.jsonl> [--chrome trace.json]",
                "hung_vs_progressing": (
                    "a heartbeat mtime older than PJ_HEARTBEAT_STALE_S "
                    "means hung (retry now); fresh means progressing "
                    "(the TPU pass extends the stage deadline)"
                ),
                "disabled_by_default": True,
            },
            # The query-serving surface (README "Query serving"):
            # store tiers, the exact-vs-approx answer contract, the
            # JSONL request format, and exit codes. Attach a store dir
            # via --serve-store for its persisted counters.
            "serving": {
                "command": "pjtpu serve <graph> [--store-dir DIR] "
                           "[--queries FILE|-]",
                "store_tiers": {
                    "hot": "device-resident rows, LRU (default capacity "
                           "128; --hot-rows)",
                    "warm": "host-RAM LRU of materialized rows (default "
                            "4096; --warm-rows)",
                    "cold": "checkpoint batches via the persisted "
                            "manifest — O(1) source lookup; any solve "
                            "--checkpoint-dir is attachable",
                },
                "query_format": (
                    'JSONL, one object per line: {"id": ..., '
                    '"source": S, "dst": T | [T, ...] | null (full '
                    'row), "mode": "exact" | "approx"}'
                ),
                "answer_contract": (
                    "exact=true answers are bitwise the solver's rows "
                    "(max_error 0); exact=false landmark answers carry "
                    "|answer - exact| <= max_error, never unflagged; "
                    "stale=true answers (pre-update rows) additionally "
                    "carry a landmark-derived max_error drift estimate"
                ),
                # Device-resident lookups (ISSUE 16): the planner
                # prices the two lookup routes per aggregated batch;
                # forcing either path reproduces the other bit for bit.
                "device_lookup": {
                    "flags": "--device-lookup auto|on|off "
                             "[--batch-window W] [--batch-wait-ms MS]",
                    "paths": {
                        "host_lookup": "per-source tier walk (hot/"
                                       "warm/cold), the measured "
                                       "default on cpu",
                        "device_lookup": "megabatched gathers over the "
                                         "stacked [B, V] hot tile + "
                                         "on-device landmark bounds, "
                                         "one launch per query class "
                                         "per batch",
                    },
                    "contract": (
                        "bit-for-bit identical answers on every path: "
                        "exact hits move f32 bits; raw landmark bounds "
                        "(add/sub + min/max, f64) compute on device, "
                        "the tolerance widening and estimate finishing "
                        "always run on host through shared helpers; "
                        "TPU (no native f64) keeps landmark bounds on "
                        "host — the why-line says so"
                    ),
                    "micro_batching": (
                        "--listen requests convoy-combine into device-"
                        "width engine batches (leader drains up to "
                        "--batch-window pending peers; wait 0 means an "
                        "idle server pays zero added latency); "
                        "batch_width_p50/p99 land in serve_stats.json"
                    ),
                    "decision": "engine serve summary + bench detail "
                                "record the planner why-line "
                                "(lookup.auto_decision)",
                },
                "landmark_picker": (
                    "--landmark-picker uniform|coverage|boundary — "
                    "coverage weights pivot sampling by vertex degree "
                    "(hub coverage for skewed graphs), boundary samples "
                    "partition-frontier vertices (corridor/mesh graphs); "
                    "uniform stays the reproducible default"
                ),
                # The certified approximate tier (ISSUE 17): a (1+eps)
                # hopset answers APSP batches past the exact-scale wall
                # with a certified per-answer error bound.
                "approximate_tier": {
                    "flags": "--hopset [--approx-epsilon E] "
                             "[--approx-beta B] [--error-budget R] "
                             "[--miss-policy hopset] "
                             "[--shed-policy hopset|priced]",
                    "route": (
                        "hopset+bf: beta-bounded-hop Bellman-Ford over "
                        "the graph seeded with pivot-relay rows; the "
                        "planner qualifies it only under a finite "
                        "--error-budget and auto-picks the cheapest "
                        "exact route at budget 0"
                    ),
                    "certificate": (
                        "every hopset answer carries exact=false plus a "
                        "finite per-entry max_error (converged batches "
                        "certify to f32 rounding; unconverged batches "
                        "certify via pivot-closure relay bounds); "
                        "unreachable is never silently bounded — "
                        "unproven infinity reports max_error inf"
                    ),
                    "composition": (
                        "when both a landmark interval and a hopset "
                        "interval cover the same answer the engine "
                        "intersects them — the tighter certified bound "
                        "wins, never an unflagged estimate"
                    ),
                    "construction": (
                        "k ~ sqrt(V) pivots (uniform/coverage/boundary "
                        "picker), beta-bounded forward+reverse pivot "
                        "rows built by the ordinary relax sweeps; fleet "
                        "construction shards pivots over workers and is "
                        "bitwise-identical to a single worker; persisted "
                        "digest-guarded as hopset.npz next to "
                        "landmarks.npz"
                    ),
                    "pricing": (
                        "hopset+bf appears in cost_observatory."
                        "priced_routes beside the exact routes (explicit "
                        "unpriced marker until profiled) — the exact-vs-"
                        "approx price comparison the budgeted planner "
                        "consults"
                    ),
                },
                # The traffic front end (ISSUE 15, README "Traffic
                # front end"): socket serving with designed overload
                # behavior — admission bounds, deadline drops,
                # burn-triggered certified shedding, SIGTERM drain.
                "listen": {
                    "command": "pjtpu serve <graph> --listen HOST:PORT "
                               "[--max-connections N] [--max-inflight "
                               "N] [--shed-policy landmark|hopset|"
                               "priced|reject|off] "
                               "[--drain-timeout S]",
                    "protocol": (
                        "newline-delimited JSON over TCP; one header "
                        "line {protocol: 'pjtpu-serve/1', graph_digest, "
                        "shed_policy} per connection; requests may add "
                        "deadline_ms; {'op': 'health'} returns the "
                        "liveness document"
                    ),
                    "admission": (
                        "past --max-connections / --max-inflight new "
                        "work gets {'error': 'overloaded', "
                        "'retry_after_ms': ...} instead of an unbounded "
                        "queue; a deadline_ms request may wait for a "
                        "slot up to its own deadline, then drops "
                        "WITHOUT touching the engine (deadline_drops)"
                    ),
                    "shedding": (
                        "when the SLO burn-rate alert fires (and is "
                        "backed by >= --shed-min-events observations in "
                        "the rule's long window — the low-traffic "
                        "guard), exact-MISS queries degrade to landmark "
                        "answers flagged {shed: true, exact: false, "
                        "max_error: ...} — certified bounds, never "
                        "unflagged; hits still answer exactly; recovers "
                        "when the burn clears; both transitions emit "
                        "slo_shed flight events"
                    ),
                    "drain": (
                        "SIGTERM stops accepting, finishes in-flight "
                        "requests under --drain-timeout, flushes "
                        "serve_stats.json + serve_live.json "
                        "(atomically), exits 0; SIGKILL leaves the last "
                        "periodic snapshots readable"
                    ),
                    "chaos_drill": "python scripts/serve_chaos_drill.py "
                                   "(fault points serve_accept / "
                                   "serve_lookup / serve_solve)",
                },
                "exit_codes": {
                    "0": "all queries answered (or clean SIGTERM drain)",
                    "1": "some queries malformed / bad arguments",
                    "2": "negative cycle during a scheduled solve",
                    "3": "corruption or abandoned stage",
                },
            },
            # The incremental-update surface (README "Incremental
            # updates"): what pjtpu update repairs, its exit codes
            # (consistent with serve/fleet), and the staleness
            # contract; attach --updates + --checkpoint-dir for a
            # dirty-set diagnosis of a concrete update file.
            "incremental": {
                "command": "pjtpu update <graph> --updates FILE "
                           "--checkpoint-dir DIR [--dry-run] "
                           "[--fleet-dir DIR]",
                "update_format": (
                    'one update per line: {"u": U, "v": V, "w": W|null} '
                    "JSON or 'U V W' text; w of null/inf removes the "
                    "edge, the last update to a pair wins"
                ),
                "repair": (
                    "re-close only dirty parts + the boundary core "
                    "(through the ordinary resilient solver), re-expand "
                    "only affected source ranges, commit per batch "
                    "through the corruption-checked checkpoint writer "
                    "under the NEW graph digest — bitwise-identical to "
                    "a fresh full solve on integer weights"
                ),
                "staleness": (
                    "while (and after) repair runs, the OLD digest's "
                    "store serves affected sources with stale: true "
                    "(repair_status.json); unaffected rows are provably "
                    "current for the updated graph and stay unflagged"
                ),
                "exit_codes": {
                    "0": "repair complete (or dry-run diagnosis printed)",
                    "1": "bad arguments, malformed update file, or no "
                         "checkpoint for this graph",
                    "2": "the update batch creates a negative cycle "
                         "(checkpoint left intact; old answers stay "
                         "stale-flagged)",
                    "3": "corruption or abandoned stage during repair",
                },
            },
            # The pipelined fan-out defaults (README "Pipelined
            # execution"): per-solve download_s / ckpt_wait_s /
            # overlap_saved_s prove the overlap in the stats output.
            "pipeline": {
                "pipeline_depth": _dc.pipeline_depth or 2,
                "pipeline_depth_auto": (
                    "None = auto: profile-tuned per (platform, shape "
                    "bucket) when the store has measured alternatives, "
                    "else 2 (observe.tuning)"
                ),
                "compilation_cache_dir": _dc.compilation_cache_dir,
                "compilation_cache_env": "PJ_COMPILE_CACHE",
                "overlap": (
                    "batch k's D2H row download + checkpoint write run "
                    "behind batch k+1's device compute; each extra "
                    "in-flight slot carries one [B, V] block of HBM "
                    "(budgeted by suggested_source_batch)"
                ),
            },
            # The cost-observatory surface (README "Cost observatory"):
            # where profiles persist, what a roofline line means, and
            # the priced route table below when a store exists.
            "cost_observatory": {
                "flags": {
                    "--profile-store": (
                        "capture XLA compiled costs per (route, "
                        "platform, shape-bucket), roofline-classify "
                        "each solve, append one record per solve to "
                        "DIR/profiles.jsonl"
                    ),
                },
                "env_default": "PJ_PROFILE_DIR",
                "offline_readers": [
                    "python scripts/cost_report.py <profile dir | "
                    "flight.jsonl>",
                    "python scripts/bench_regress.py --history "
                    "<profile dir> --last 1",
                ],
                "bound_kinds": {
                    "hbm": "analytic bytes / peak bandwidth >= analytic "
                           "flops / peak compute (gather-limited)",
                    "mxu": "compute floor above bandwidth floor "
                           "(math-limited)",
                    "host-io": "downloads + checkpoint waits (net of "
                               "pipeline overlap) dominate the wall",
                    "unknown": "no capture for this solve",
                },
            },
            # The convergence observatory (README "Convergence
            # observatory"): per-iteration introspection of the
            # iterative kernel routes — the measured substrate of
            # ROADMAP item 4 (JFR frontier compaction).
            "convergence_observatory": {
                "flags": {
                    "--convergence": (
                        "auto (on when telemetry or a profile store is "
                        "configured; otherwise the original "
                        "uninstrumented kernels compile) / true / false"
                    ),
                },
                "instrumented_routes": [
                    "sweep", "sweep-sm", "vm", "vm-blocked",
                    "vm-blocked+dw", "gs", "dia", "bucket",
                ],
                "per_iteration": [
                    "frontier_size (vertices whose distance improved)",
                    "relaxations_applied (labels improved)",
                    "residual_mass (sum of finite distance decreases)",
                ],
                "summary_fields": [
                    "iterations", "frontier_half_life",
                    "tail_fraction (frontier < 1% of V)",
                    "jfr_skippable_edge_frac",
                ],
                "heartbeat_fields": ["iter", "frontier_size", "eta_s"],
                "offline_readers": [
                    "python scripts/convergence_report.py "
                    "<profile dir | flight.jsonl>",
                    "python scripts/trace_summary.py <flight.jsonl> "
                    "--convergence",
                ],
                "evidence": "bench_artifacts/convergence_evidence.md",
            },
            # Dirty-window compaction (README "Dirty-window
            # compaction"): the route that COLLECTS the measured
            # skippable work the convergence observatory records.
            "dirty_window": {
                "flags": {
                    "--dirty-window": (
                        "auto (engage only when a profile-store "
                        "trajectory record for this graph shape shows "
                        "a collapsing frontier) / true / false"
                    ),
                    "--dw-block": "vertices per activity bit",
                },
                "route_tags": ["vm-blocked+dw", "gs+dw"],
                "counters": (
                    "exact examined vs skipped edge slots per solve "
                    "(split int32, wrap-guarded); skipped = rounds x E "
                    "- examined"
                ),
                "dispatch": (
                    "auto consults observe.convergence.dw_decision over "
                    "the profile store's kind=trajectory records "
                    "(skew-corrected jfr_skippable_edge_frac >= "
                    "0.75 and >= 8 iterations), refined by the "
                    "CostModel when both routes are priced — never "
                    "engages blindly"
                ),
                "evidence": "bench_artifacts/dw_offchip_validation.md",
            },
            # Self-driving dispatch (README "Self-driving dispatch",
            # ISSUE 14): the priced planner registry + the
            # profile-calibrated auto-tuned parameters.
            "planner": {
                "flags": {
                    "--planner": (
                        "auto/true: promote a cheaper qualified plan "
                        "above the priority incumbent when the profile "
                        "store's CostModel prices BOTH beyond the noise "
                        "band; false: pure declared priority (the "
                        "pre-registry ladder order). Forced route flags "
                        "(--fw/--dia/--gauss-seidel/--bucket/"
                        "--dirty-window true) are qualification "
                        "overrides: the forced plan is pinned first and "
                        "its mesh contracts still fail loud"
                    ),
                },
                "registry": (
                    "each kernel family declares a Plan (contract, "
                    "qualification predicate, cost hook, build, "
                    "failure policy) in paralleljohnson_tpu.planner; "
                    "dispatch picks the cheapest qualified plan and "
                    "degrades down the ranking instead of crashing"
                ),
                "noise_band": 0.25,
                "auto_tuned_parameters": {
                    "fw_tile": "hand-tuned fallback 512 (roofline)",
                    "partition_parts": (
                        "hand-tuned fallback ~sqrt(V)/8, clamp [2, 32]"
                    ),
                    "delta": (
                        "hand-tuned fallback: mean |w| x degree "
                        "heuristic (ops.bucket.auto_delta)"
                    ),
                    "source_batch": (
                        "hand-tuned fallback: device-memory budget "
                        "(suggested_source_batch); tuned values stay "
                        "capped by the budget"
                    ),
                    "pipeline_depth": "hand-tuned fallback 2",
                    "approx_beta": (
                        "hand-tuned fallback ops.hopset.auto_beta"
                        "(V, epsilon)"
                    ),
                },
                "tuner": (
                    "pjtpu tune probes candidate knob values under hard "
                    "wall-clock budgets and lands ordinary kind='plan' "
                    "records plus kind='tune' audit rows; promotion "
                    "stays behind the same 25% noise band "
                    "(paralleljohnson_tpu.tuner, ISSUE 19). Zero budget "
                    "= bitwise-identical dispatch. Idle fleet workers "
                    "and serve replicas drain tuning leases via "
                    "--tune-dir"
                ),
                "tuning": (
                    "per (platform, shape bucket) from the profile "
                    "store's kind='plan' records: the value with the "
                    "lowest recorded wall wins once >= 2 distinct "
                    "values were measured; an empty store always "
                    "resolves the hand-tuned constants; explicit "
                    "config values always win (observe.tuning)"
                ),
                "records": "kind='plan' rows in profiles.jsonl "
                           "(chosen plan + why-line + candidates with "
                           "explicit unpriced markers + resolved "
                           "params + measured wall)",
            },
        }
        # Priced route table from the persisted calibration — the
        # preview the planned dispatch registry (ROADMAP item 7) will
        # consume programmatically.
        _store_dir = (
            args.profile_store
            or os.environ.get("PJ_PROFILE_DIR")
            or ("bench_artifacts/profiles"
                if os.path.isdir("bench_artifacts/profiles") else None)
        )
        if _store_dir is not None:
            try:
                from paralleljohnson_tpu.observe import (
                    CostModel,
                    ProfileStore,
                )

                _store = ProfileStore(_store_dir)
                _model = CostModel.fit(_store)
                info["cost_observatory"]["store"] = str(_store.path)
                info["cost_observatory"]["records"] = len(_store.records())
                _table = _model.table()
                # Explicit unpriced markers (ISSUE 14 satellite): every
                # registry route with no profile samples appears, never
                # silently omitted — "cheap" and "unmeasured" must stay
                # distinguishable.
                from paralleljohnson_tpu.planner import KNOWN_ROUTES

                _priced_names = {e["route"] for e in _table}
                _table.extend(
                    {"route": r, "platform": None, "unpriced": True}
                    for r in KNOWN_ROUTES if r not in _priced_names
                )
                info["cost_observatory"]["priced_routes"] = _table
            except Exception as e:  # noqa: BLE001 — report, don't die
                info["cost_observatory"]["store_error"] = (
                    f"{type(e).__name__}: {e}"
                )
                _model = None
        else:
            _model = None
        if args.serve_store is not None:
            # Persisted serving state: each graph subdirectory's
            # serve_stats.json (written by QueryEngine.close) plus the
            # landmark index size, so capacity / hit-rate / landmark
            # count are reportable without starting a request loop.
            from pathlib import Path as _Path

            from paralleljohnson_tpu.serve import SERVE_STATS_FILENAME

            root = _Path(args.serve_store)
            stores = []
            for d in sorted({root, *root.glob("graph_*")}):
                entry = {}
                stats_f = d / SERVE_STATS_FILENAME
                if stats_f.exists():
                    try:
                        entry.update(json.loads(
                            stats_f.read_text(encoding="utf-8")
                        ))
                    except ValueError:
                        entry["error"] = "unreadable serve_stats.json"
                lm_f = d / "landmarks.npz"
                if lm_f.exists():
                    try:
                        with np.load(lm_f) as z:
                            entry["landmarks_persisted"] = int(
                                len(z["sources"])
                            )
                    except Exception:  # noqa: BLE001 — report, don't die
                        entry["landmarks_persisted"] = "unreadable"
                hs_f = d / "hopset.npz"
                if hs_f.exists():
                    # Persisted approximate tier (ISSUE 17): report the
                    # knobs that define the certificate without loading
                    # the row matrices.
                    try:
                        with np.load(hs_f) as z:
                            _piv = z["pivots"]
                            _rng = np.arange(len(_piv))
                            _edges = int(
                                np.isfinite(z["fwd"]).sum()
                                + np.isfinite(z["rev"]).sum()
                                - np.isfinite(z["fwd"][_rng, _piv]).sum()
                                - np.isfinite(z["rev"][_rng, _piv]).sum()
                            ) if len(_piv) else 0
                            entry["hopset_persisted"] = {
                                "epsilon": float(z["epsilon"]),
                                "beta": int(z["beta"]),
                                "k": int(len(_piv)),
                                "edges": _edges,
                                "converged": bool(z["converged"]),
                            }
                    except Exception:  # noqa: BLE001 — report, don't die
                        entry["hopset_persisted"] = "unreadable"
                if entry:
                    entry["dir"] = str(d)
                    stores.append(entry)
            info["serving"]["stores"] = stores

        if args.graph is not None:
            # Per-graph route diagnosis: the SAME predicates dispatch
            # consults, so "why did my solve pick route X" is answerable
            # without running a solve (and, on-chip, without burning
            # tunnel time on a mis-routed measurement).
            from paralleljohnson_tpu.backends import get_backend
            from paralleljohnson_tpu.config import SolverConfig

            g = load_graph(args.graph)
            be = get_backend(
                "jax", SolverConfig(profile_store=args.profile_store)
            )
            dg = be.upload(g)
            dia_lay = be.dia_bundle(dg)
            info["graph"] = {
                "nodes": g.num_nodes,
                "edges": g.num_real_edges,
                "max_degree": dg.max_degree,
                "negative_weights": bool(g.has_negative_weights),
                "routes": {
                    "dense": bool(be._use_dense(dg)),
                    # The B=V dense closure (blocked min-plus FW) and
                    # the condensed partitioned route, both at the
                    # full-APSP batch width their auto gates consider.
                    "fw": bool(be._use_fw(dg, g.num_nodes)),
                    "dia": bool(be._use_dia(dg)),
                    "bucket": bool(be._use_bucket(dg)),
                    "gauss_seidel": bool(be._use_gs(dg)),
                    "dirty_window": bool(
                        be._use_dw(dg, min(128, max(g.num_nodes, 1)))
                    ),
                    "frontier": bool(be._use_frontier(dg)),
                    "edge_shard": bool(be._use_edge_shard(dg)),
                    # A --predecessors solve takes the SAME route above
                    # plus one tight-edge extraction pass ("<route>+pred")
                    # — or the legacy argmin sweep when extraction is off.
                    "pred": (
                        "extract" if be._use_pred_extraction() else "sweep"
                    ),
                },
                "dia_qualifies": dia_lay is not None,
                "dia_offsets": (
                    list(dia_lay["offsets"]) if dia_lay is not None else None
                ),
                "low_degree_family": bool(be._low_degree_family(dg)),
                "dw_decision": be._dw_decision(
                    dg, min(128, max(g.num_nodes, 1))
                ),
            }
            from paralleljohnson_tpu.solver import ParallelJohnsonSolver

            # Planner preview (ISSUE 14 satellite): the decision the
            # registry would make for this graph at the fan-out width —
            # chosen plan + why-line + candidate table (with explicit
            # unpriced markers), no kernel built.
            try:
                info["graph"]["plan"] = be.plan_preview(
                    dg, min(128, max(g.num_nodes, 1))
                )
            except Exception as e:  # noqa: BLE001 — report, don't die
                info["graph"]["plan"] = {
                    "error": f"{type(e).__name__}: {e}"
                }
            info["graph"]["routes"]["partitioned"] = bool(
                ParallelJohnsonSolver(
                    SolverConfig(), backend=be
                )._use_partitioned(g, np.arange(g.num_nodes))
            )
            if _model is not None and _model.entries:
                # Price THIS graph on every calibrated route: predicted
                # seconds at B=1 (the SSSP shape) and at the full
                # fan-out width — what dispatch would compare.
                priced = {}
                for entry in _model.table():
                    route = entry["route"]
                    p1 = _model.predict(
                        route, num_edges=g.num_real_edges, batch=1,
                        platform=entry["platform"],
                    )
                    pb = _model.predict(
                        route, num_edges=g.num_real_edges,
                        batch=min(128, g.num_nodes),
                        platform=entry["platform"],
                    )
                    if p1 is not None:
                        priced[f"{route}@{entry['platform']}"] = {
                            "predicted_s_b1": round(p1["predicted_s"], 6),
                            "predicted_s_b128": (
                                round(pb["predicted_s"], 6)
                                if pb is not None else None
                            ),
                            "calibration_n": entry["n"],
                        }
                info["graph"]["priced_routes"] = priced
            # Knob provenance (ISSUE 19 satellite): where each tunable's
            # effective value for THIS shape bucket comes from — seed /
            # cpu-calibrated / tuner-promoted — with the profile-store
            # line number of the backing record when one exists.
            try:
                from paralleljohnson_tpu.tuner import provenance_table

                info["graph"]["tuned_knobs"] = provenance_table(
                    store_dir=_store_dir,
                    num_nodes=g.num_nodes,
                    num_edges=g.num_real_edges,
                    config=SolverConfig(profile_store=args.profile_store),
                )
            except Exception as e:  # noqa: BLE001 — report, don't die
                info["graph"]["tuned_knobs"] = {
                    "error": f"{type(e).__name__}: {e}"
                }
        if args.updates is not None:
            # Dirty-set diagnosis of a concrete update file — the same
            # diagnose() pjtpu update runs, no repair work (the state
            # is built once and persisted if absent).
            if args.graph is None or args.checkpoint_dir is None:
                info["incremental"]["diagnosis_error"] = (
                    "--updates needs a graph spec and --checkpoint-dir"
                )
            else:
                try:
                    from paralleljohnson_tpu.incremental import (
                        IncrementalState,
                        diagnose,
                        load_updates,
                    )
                    from paralleljohnson_tpu.utils.checkpoint import (
                        BatchCheckpointer,
                        graph_digest,
                    )

                    _g = load_graph(args.graph)
                    _digest = graph_digest(_g)
                    _ck = BatchCheckpointer(
                        args.checkpoint_dir, graph_key=_digest
                    )
                    _st = IncrementalState.load(
                        _ck.dir, expect_digest=_digest
                    )
                    if _st is None:
                        _st = IncrementalState.build(_g)
                        _st.save(_ck.dir)
                    _g2, _upd_report = _g.apply_edge_updates(
                        load_updates(args.updates)
                    )
                    info["incremental"]["diagnosis"] = {
                        "checkpoint_batches": len(
                            _ck.completed_batches()
                        ),
                        "report": _upd_report.as_dict(),
                        "dirty_set": diagnose(
                            _st, _upd_report.changed_edges
                        ).as_dict(),
                    }
                except (ValueError, FileNotFoundError) as e:
                    info["incremental"]["diagnosis_error"] = (
                        f"{type(e).__name__}: {e}"
                    )
        print(json.dumps(info, indent=None if args.as_json else 2))
        return 0

    from paralleljohnson_tpu.utils.profiling import device_trace

    cfg = None
    try:
        cfg = _config(args)
        if args.command == "solve":
            g = load_graph(args.graph)
            sources = None
            if args.sources is not None:
                sources = np.array([int(s) for s in args.sources.split(",")])
            elif args.num_sources is not None:
                sources = np.arange(args.num_sources)
            if args.reduce is not None:
                unsupported = [
                    flag for flag, on in [
                        ("--predecessors", args.predecessors),
                        ("--output", args.output is not None),
                        ("--validate", args.validate),
                        ("--checkpoint-dir", args.checkpoint_dir is not None),
                    ] if on
                ]
                if unsupported:
                    # Reject rather than silently drop: rows are reduced on
                    # device and never materialized, so there is nothing to
                    # save or oracle-check.
                    print(
                        f"error: --reduce does not support "
                        f"{', '.join(unsupported)}",
                        file=sys.stderr,
                    )
                    return 1
                with device_trace(args.profile, cfg.telemetry):
                    red = ParallelJohnsonSolver(cfg).solve_reduced(
                        g, sources=sources, reduce_rows=args.reduce
                    )
                _write_metrics(red.stats, args)
                if args.log_stats:
                    from paralleljohnson_tpu.utils.profiling import log_stats

                    log_stats(red.stats, label="solve--reduce")
                vals = [
                    v.tolist() if hasattr(v, "tolist") else v
                    for v in red.values
                ]
                payload = {"reducer": args.reduce, "batches": len(vals),
                           "values": vals, **red.stats.as_dict()}
                print(json.dumps(payload) if args.as_json else
                      f"{args.reduce}: {vals}")
                return 0
            if ((cfg.error_budget > 0 or cfg.hopset is True)
                    and not args.predecessors):
                # Budgeted solve (ISSUE 17): the planner arbitrates
                # exact vs the certified hopset+bf tier. Budget 0
                # never reaches here — exact is the only honest
                # answer, and the ordinary path below serves it.
                from paralleljohnson_tpu.solver.approx import (
                    ApproxResult,
                    solve_with_budget,
                )

                with device_trace(args.profile, cfg.telemetry):
                    res, _decision = solve_with_budget(
                        g, sources, config=cfg, telemetry=cfg.telemetry
                    )
                if isinstance(res, ApproxResult):
                    _report_approx(res, args)
                else:
                    _report(res, args)
                return 0
            with device_trace(args.profile, cfg.telemetry):
                res = ParallelJohnsonSolver(cfg).solve(
                    g, sources=sources, predecessors=args.predecessors
                )
            _report(res, args)
        elif args.command == "sssp":
            g = load_graph(args.graph)
            with device_trace(args.profile, cfg.telemetry):
                res = ParallelJohnsonSolver(cfg).sssp(
                    g, args.source, predecessors=args.predecessors
                )
            _report(res, args)
        elif args.command == "serve":
            if args.route:
                # Router mode (ISSUE 18): no graph, no engine — just
                # the consistent-hash forwarder over the fleet's
                # membership records. ``--listen`` picks the bind
                # address (ephemeral port by default so drills can
                # parse the announce line).
                from paralleljohnson_tpu.serve import (
                    PROTOCOL,
                    FleetRouter,
                    parse_listen,
                )

                host, port = parse_listen(args.listen or "127.0.0.1:0")
                router = FleetRouter(
                    args.route, host=host, port=port,
                    stale_after_s=(args.replica_stale
                                   if args.replica_stale is not None
                                   else 5.0),
                    retry_after_ms=args.retry_after_ms,
                    telemetry=_telemetry(args, label="router"),
                    trace_sample=args.trace_sample,
                ).start()
                table = router.table
                print(json.dumps({
                    "listening": f"{router.address()[0]}:"
                                 f"{router.address()[1]}",
                    "host": router.address()[0],
                    "port": router.address()[1],
                    "protocol": PROTOCOL,
                    "router": True,
                    "fleet_dir": str(args.route),
                    "epoch": (table.epoch if table is not None else 0),
                }), flush=True)
                router.run_until_shutdown()
                return 0
            if args.graph is None:
                print(
                    "error: pjtpu serve requires a GRAPH positional "
                    "(or --route FLEET_DIR for router mode)",
                    file=sys.stderr,
                )
                return 1
            from paralleljohnson_tpu.serve import (
                DEFAULT_HOT_ROWS,
                DEFAULT_WARM_ROWS,
                LandmarkIndex,
                QueryEngine,
                TileStore,
            )

            g = load_graph(args.graph)
            store = TileStore(
                args.store_dir, g,
                hot_rows=(DEFAULT_HOT_ROWS if args.hot_rows is None
                          else args.hot_rows),
                warm_rows=(DEFAULT_WARM_ROWS if args.warm_rows is None
                           else args.warm_rows),
            )
            landmarks = None
            k = args.landmarks or (
                16 if args.miss_policy == "landmark"
                or (args.listen and args.shed_policy == "landmark") else 0
            )
            if k > 0:
                if store.ckpt is not None:
                    landmarks = LandmarkIndex.load(
                        store.ckpt.dir, expect_digest=store.digest
                    )
                    if landmarks is not None and landmarks.k != k:
                        landmarks = None  # stale size: rebuild
                if landmarks is None:
                    landmarks = LandmarkIndex.build(
                        g, k, config=cfg, picker=args.landmark_picker)
                    if store.ckpt is not None:
                        landmarks.save(store.ckpt.dir)
            # The certified approximate tier (ISSUE 17): load-or-build
            # the persisted hopset exactly like the landmark index —
            # digest-guarded, knob-mismatch means rebuild. 'priced'
            # shedding runs on whichever certified tiers exist, so it
            # does not force a build by itself.
            hopset = None
            if (args.miss_policy == "hopset"
                    or (args.listen and args.shed_policy == "hopset")
                    or cfg.hopset is True):
                from paralleljohnson_tpu.ops.hopset import (
                    Hopset,
                    build_hopset,
                )

                if store.ckpt is not None:
                    hopset = Hopset.load(
                        store.ckpt.dir, expect_digest=store.digest
                    )
                    if (hopset is not None
                            and (hopset.epsilon != cfg.approx_epsilon
                                 or (cfg.approx_beta is not None
                                     and hopset.beta != cfg.approx_beta))):
                        hopset = None  # stale knobs: rebuild
                if hopset is None:
                    hopset = build_hopset(
                        g, epsilon=cfg.approx_epsilon,
                        beta=cfg.approx_beta,
                        picker=args.landmark_picker,
                        telemetry=cfg.telemetry,
                    )
                    if store.ckpt is not None:
                        hopset.save(store.ckpt.dir)
            from paralleljohnson_tpu.observe.live import SLO

            engine = QueryEngine(
                g, store, landmarks=landmarks, hopset=hopset, config=cfg,
                miss_policy=args.miss_policy,
                device_lookup=args.device_lookup,
                slo=SLO(name="serve", latency_ms=args.slo_p99_ms,
                        latency_pct=99.0,
                        availability=args.slo_availability),
                stats_interval_s=args.stats_interval,
            )
            if args.listen:
                # Traffic front end (README "Traffic front end"): a
                # threaded socket server in the foreground until
                # SIGTERM/SIGINT, then a graceful drain (exit 0).
                from paralleljohnson_tpu.serve import (
                    PROTOCOL,
                    ServeFrontend,
                    parse_listen,
                )

                host, port = parse_listen(args.listen)
                fe_kw = {}
                if args.batch_window is not None:
                    fe_kw["batch_window"] = args.batch_window
                if args.batch_wait_ms is not None:
                    fe_kw["batch_wait_ms"] = args.batch_wait_ms
                frontend = ServeFrontend(
                    engine, host=host, port=port,
                    max_connections=args.max_connections,
                    max_inflight=args.max_inflight,
                    shed_policy=args.shed_policy,
                    **fe_kw,
                    drain_timeout_s=args.drain_timeout,
                    retry_after_ms=args.retry_after_ms,
                    shed_min_events=args.shed_min_events,
                    fault_plan=cfg.fault_plan,
                    heartbeat_file=args.heartbeat_file,
                    max_inflight_per_client=args.max_inflight_per_client,
                    http=args.http,
                    fleet_dir=args.fleet_dir,
                    replica_id=args.replica_id,
                    fleet_heartbeat_s=args.replica_heartbeat,
                    tune_dir=args.tune_dir,
                    trace_sample=args.trace_sample,
                ).start()
                # The announce line scripts/chaos drills parse for the
                # bound (possibly ephemeral) port.
                print(json.dumps({
                    "listening": f"{frontend.address[0]}:"
                                 f"{frontend.address[1]}",
                    "host": frontend.address[0],
                    "port": frontend.address[1],
                    "protocol": PROTOCOL,
                    "shed_policy": args.shed_policy,
                    "max_connections": args.max_connections,
                    "max_inflight": args.max_inflight,
                    "replica_id": frontend.replica_id,
                    "http": args.http,
                }), flush=True)
                frontend.run_until_shutdown()
                if getattr(args, "metrics_file", None):
                    engine.write_metrics(args.metrics_file,
                                         labels={"command": "serve"})
                if args.summary:
                    print(json.dumps(engine.serve_summary()),
                          file=sys.stderr)
                return 0
            stream = (
                sys.stdin if args.queries == "-"
                else open(args.queries, encoding="utf-8")
            )
            n_errors = 0
            try:

                def answer(buf: list) -> int:
                    responses, errs = engine.query_lines(buf)
                    for r in responses:
                        print(json.dumps(r), flush=True)
                    return errs

                buf: list = []
                for line in stream:
                    if not line.strip():
                        continue
                    buf.append(line)
                    if len(buf) >= max(1, args.batch_queries):
                        n_errors += answer(buf)
                        buf = []
                if buf:
                    n_errors += answer(buf)
            finally:
                if stream is not sys.stdin:
                    stream.close()
                engine.close()
            if getattr(args, "metrics_file", None):
                # The SERVE metric table (pjtpu_queries_total,
                # pjtpu_query_latency_*), not the solver's.
                engine.write_metrics(args.metrics_file,
                                     labels={"command": "serve"})
            if args.summary:
                print(json.dumps(engine.serve_summary()), file=sys.stderr)
            return 1 if n_errors else 0
        elif args.command == "update":
            from paralleljohnson_tpu.incremental import (
                IncrementalState,
                diagnose,
                load_updates,
                repair_checkpoint,
            )

            if not args.checkpoint_dir:
                print(
                    "error: pjtpu update requires --checkpoint-dir "
                    "(the solved checkpoint to repair)",
                    file=sys.stderr,
                )
                return 1
            g = load_graph(args.graph)
            updates = load_updates(args.updates)
            if args.dry_run:
                from paralleljohnson_tpu.utils.checkpoint import (
                    BatchCheckpointer,
                    graph_digest,
                )

                digest = graph_digest(g)
                ck = BatchCheckpointer(args.checkpoint_dir,
                                       graph_key=digest)
                if not ck.manifest():
                    print(
                        f"error: {ck.dir}: no completed batches for "
                        "this graph — nothing to diagnose",
                        file=sys.stderr,
                    )
                    return 1
                state = IncrementalState.load(ck.dir,
                                              expect_digest=digest)
                if state is None:
                    state = IncrementalState.build(
                        g, num_parts=args.partition_parts, config=cfg
                    )
                    state.save(ck.dir)
                _g2, report = g.apply_edge_updates(updates)
                payload = {
                    "dry_run": True,
                    "report": report.as_dict(),
                    "dirty_set": diagnose(
                        state, report.changed_edges
                    ).as_dict(),
                }
                print(json.dumps(payload))
                return 0
            if args.fleet_dir:
                from paralleljohnson_tpu.incremental.fleet import (
                    run_in_process_repair_fleet,
                )

                result = run_in_process_repair_fleet(
                    args.checkpoint_dir, g, updates,
                    coordinator_dir=args.fleet_dir,
                    workers=args.fleet_workers, config=cfg,
                    num_parts=args.partition_parts,
                )
            else:
                result = repair_checkpoint(
                    args.checkpoint_dir, g, updates, config=cfg,
                    num_parts=args.partition_parts,
                    strategy=args.strategy,
                )
            payload = result.as_dict()
            if args.as_json:
                print(json.dumps(payload))
            else:
                if result.trivial:
                    print("update was a no-op (no effective edge "
                          "changes); checkpoint unchanged")
                else:
                    print(
                        f"repaired {payload['batches_rewritten']} "
                        f"batches under digest {result.new_digest}: "
                        f"{payload['rows_recomputed']} rows re-expanded"
                        f", {payload['rows_patched']} column-patched, "
                        f"{payload['rows_copied']} copied bitwise"
                    )
                    print(
                        f"  dirty parts closed: "
                        f"{payload['dirty_parts_closed']} of "
                        f"{payload['parts_total']}"
                        + (" (+ boundary core)"
                           if payload["core_recomputed"] else "")
                    )
                    print(
                        f"  walls: closures "
                        f"{payload['closures_s'] * 1e3:.1f} ms, expand "
                        f"{payload['expand_s'] * 1e3:.1f} ms, io "
                        f"{payload['io_s'] * 1e3:.1f} ms"
                    )
        elif args.command == "batch":
            if args.predecessors:
                print("error: batch mode does not support --predecessors",
                      file=sys.stderr)
                return 1
            graphs = random_graph_batch(args.count, args.nodes, args.p,
                                        seed=args.seed)
            with device_trace(args.profile, cfg.telemetry):
                results = ParallelJohnsonSolver(cfg).solve_batch(graphs)
            stats = results[0].stats
            _write_metrics(stats, args)
            if args.log_stats:
                from paralleljohnson_tpu.utils.profiling import log_stats

                log_stats(stats, label="batch")
            payload = {"graphs": len(results),
                       "matrix_shape": list(results[0].dist.shape),
                       **stats.as_dict()}
            print(json.dumps(payload) if args.as_json else
                  f"{len(results)} graphs solved; " +
                  f"{stats.total_seconds:.3f}s total, "
                  f"{stats.edges_relaxed:,} edges relaxed")
    except NegativeCycleError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    except (SolveCorruptionError, StageAbandonedError) as e:
        # Resilience-layer terminal failures: corruption the sanity
        # guard caught, or a stage the watchdog abandoned on every
        # attempt — diagnosable message, distinct exit code.
        print(f"error: {e}", file=sys.stderr)
        return 3
    except (ValueError, FileNotFoundError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    finally:
        # Stop the heartbeat, export the Chrome trace, close the flight
        # file — ALSO on the error paths: the telemetry of a failed
        # solve is the artifact the flags exist for.
        tel = getattr(cfg, "telemetry", None)
        if tel is not None:
            tel.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
