"""Self-proposing planner: a budgeted probe tuner over the dispatch knobs.

The round-14 planner promotes a challenger route only when the profile
store already holds measured evidence for it — which means the fleet only
ever learns from traffic it happened to serve.  This module closes the
loop: it *proposes* candidate values for every tunable knob a
:class:`~paralleljohnson_tpu.planner.Plan` declares (``Plan.tunables``),
*measures* them with budgeted probe solves, and lands the measurements as
ordinary ``kind:"plan"`` profile records plus a ``kind:"tune"`` audit
record per probe.  Promotion stays where it always was: the observatory's
single calibrated-challenger rule (:data:`observe.tuning.TUNE_NOISE_BAND`)
decides whether a probed value dislodges the seed, and ``planner-audit``
explains it with the same why-lines it prints for route promotion.

Three invariants the tests pin:

* **Budget is a wall, not a suggestion.**  Every probe runs under a hard
  wall-clock cap (``budget_s``).  A probe that outlives the cap is
  abandoned, its profile records are *discarded* (they never reach the
  store, so a censored value is structurally unpromotable), and a
  ``censored: true`` tune record documents the attempt.
* **Zero budget is a no-op.**  ``tune_bucket(..., bucket_budget_s=0)``
  returns without touching the store; dispatch with a zero tuning budget
  is bitwise-identical to dispatch without the tuner.
* **Proposals are deterministic.**  Candidate generation is a pure
  function of the shape bucket, the config seed, and the (sorted) set of
  values already measured in that bucket — two workers proposing for the
  same bucket propose the same list in the same order.

Idle-capacity farm (ISSUE 19): :func:`plan_tuning_fleet` writes a
round-15 coordinator plan whose leases are (knob x candidate-chunk)
jobs, chunk sizes priced from the CostModel; :func:`run_tuning_worker`
and the one-shot :func:`try_tuning_lease` (the hook fleet workers and
serve replicas call when idle) claim leases, probe into per-worker shard
stores, and commit under the coordinator's digest guard;
:func:`harvest_tuning` merges committed shards into the real store.
"""

from __future__ import annotations

import dataclasses
import json
import math
import shutil
import tempfile
import threading
import time
from pathlib import Path
from typing import Any, Callable, Sequence

import numpy as np

from paralleljohnson_tpu import planner as _planner
from paralleljohnson_tpu.config import SolverConfig
from paralleljohnson_tpu.observe import current_platform
from paralleljohnson_tpu.observe.costs import shape_bucket
from paralleljohnson_tpu.observe.store import CostModel, ProfileStore
from paralleljohnson_tpu.observe.tuning import (
    DEFAULT_FW_TILE,
    DEFAULT_PIPELINE_DEPTH,
    TUNABLE_PARAMS,
    TUNE_NOISE_BAND,
    cached_records,
    param_provenance,
    tuned_value,
)

__all__ = [
    "KnobSpec",
    "KNOB_SPECS",
    "ProbeResult",
    "declared_tunables",
    "propose_candidates",
    "run_probe",
    "tune_bucket",
    "plan_tuning_fleet",
    "run_tuning_worker",
    "try_tuning_lease",
    "harvest_tuning",
]

TUNE_SPEC_PREFIX = "tune:"
HARVESTED_FILE = "harvested.json"

# Floor under the per-probe cap when pricing lease sizes: even a probe the
# model predicts as instant pays Python/trace overhead.
MIN_PRICED_PROBE_S = 0.05


# ---------------------------------------------------------------------------
# Knob registry


def _pad128(v: int) -> int:
    return 128 * max(1, math.ceil(max(1, int(v)) / 128))


def _cand_fw_tile(v: int, e: int, seed: Any) -> list[int]:
    pad = _pad128(v)
    tiles = {128, 256, 384, 512, pad}
    if isinstance(seed, int) and seed >= 128:
        tiles.add(min(seed, pad))
    return sorted(t for t in tiles if 128 <= t <= pad)


def _cand_partition_parts(v: int, e: int, seed: Any) -> list[int]:
    vals = {p for p in (2, 4, 8, 16, 32) if 2 * p <= max(4, v)}
    if isinstance(seed, int) and seed >= 2:
        vals.add(seed)
    return sorted(vals)


def _cand_delta(v: int, e: int, seed: Any) -> list[float]:
    base = float(seed) if seed else 1.0
    return sorted({round(base * m, 9) for m in (0.25, 0.5, 1.0, 2.0, 4.0)})


def _cand_source_batch(v: int, e: int, seed: Any) -> list[int]:
    out, b = [], 8
    while b <= max(8, v) and len(out) < 6:
        out.append(b)
        b *= 2
    if isinstance(seed, int) and seed >= 1:
        out.append(min(seed, max(8, v)))
    return sorted(set(out))


def _cand_pipeline_depth(v: int, e: int, seed: Any) -> list[int]:
    vals = {1, 2, 3, 4}
    if isinstance(seed, int) and seed >= 1:
        vals.add(seed)
    return sorted(vals)


def _cand_approx_beta(v: int, e: int, seed: Any) -> list[int]:
    b = int(seed) if seed else 6
    return sorted({max(2, b // 2), max(2, b), max(2, 2 * b)})


def _seed_fw_tile(config: SolverConfig, v: int, e: int) -> int:
    return int(config.fw_tile) if config.fw_tile else DEFAULT_FW_TILE


def _seed_partition_parts(config: SolverConfig, v: int, e: int) -> int:
    if config.partition_parts:
        return int(config.partition_parts)
    return max(2, min(32, int(math.isqrt(max(4, v))) // 2 or 2))


def _seed_delta(config: SolverConfig, v: int, e: int) -> float:
    return float(config.delta) if config.delta else 1.0


def _seed_source_batch(config: SolverConfig, v: int, e: int) -> int:
    if config.source_batch_size:
        return int(config.source_batch_size)
    return max(8, min(64, v))


def _seed_pipeline_depth(config: SolverConfig, v: int, e: int) -> int:
    if config.pipeline_depth:
        return int(config.pipeline_depth)
    return DEFAULT_PIPELINE_DEPTH


def _seed_approx_beta(config: SolverConfig, v: int, e: int) -> int:
    if config.approx_beta:
        return int(config.approx_beta)
    from paralleljohnson_tpu.ops.hopset import auto_beta

    return auto_beta(v, float(config.approx_epsilon))


def _probe_solve(graph, sources, config: SolverConfig) -> None:
    from paralleljohnson_tpu.solver.johnson import ParallelJohnsonSolver

    ParallelJohnsonSolver(config).solve(graph, sources)


def _probe_approx(graph, sources, config: SolverConfig) -> None:
    from paralleljohnson_tpu.solver.approx import solve_with_budget

    solve_with_budget(
        graph, sources, config=config,
        error_budget=float(config.approx_epsilon),
    )


@dataclasses.dataclass(frozen=True)
class KnobSpec:
    """How to probe one tunable knob: which config field carries a
    candidate value, which route overrides pin the plan that consumes it,
    how many sources a representative probe solves, and the deterministic
    candidate/seed generators.  ``validate`` mirrors the resolve-time
    filter in ``observe.tuning`` so the tuner never probes a value
    dispatch would refuse to trust."""

    name: str
    config_field: str
    plan: str                       # plan whose tunables declare this knob
    overrides: dict[str, Any]       # force the consuming route during probes
    candidates: Callable[[int, int, Any], list]
    seed: Callable[[SolverConfig, int, int], Any]
    probe: Callable[[Any, np.ndarray, SolverConfig], None] = _probe_solve
    probe_batch: Callable[[int], int] = lambda v: v
    validate: Callable[[Any], bool] | None = None


KNOB_SPECS: dict[str, KnobSpec] = {
    "fw_tile": KnobSpec(
        name="fw_tile", config_field="fw_tile", plan="fw",
        overrides={"fw": True, "mesh_shape": (1,)},
        candidates=_cand_fw_tile, seed=_seed_fw_tile,
        validate=lambda x: isinstance(x, int) and x >= 128 and x % 128 == 0,
    ),
    "partition_parts": KnobSpec(
        name="partition_parts", config_field="partition_parts",
        plan="condensed+fw", overrides={"partitioned": True},
        candidates=_cand_partition_parts, seed=_seed_partition_parts,
        validate=lambda x: isinstance(x, int) and x >= 2,
    ),
    "delta": KnobSpec(
        name="delta", config_field="delta", plan="bucket",
        overrides={"bucket": True},
        candidates=_cand_delta, seed=_seed_delta,
        probe_batch=lambda v: 1,
        validate=lambda x: isinstance(x, (int, float)) and x > 0,
    ),
    "source_batch": KnobSpec(
        name="source_batch", config_field="source_batch_size",
        plan="standard", overrides={"partitioned": False},
        candidates=_cand_source_batch, seed=_seed_source_batch,
        validate=lambda x: isinstance(x, int) and x >= 1,
    ),
    "pipeline_depth": KnobSpec(
        name="pipeline_depth", config_field="pipeline_depth",
        plan="standard", overrides={"partitioned": False},
        candidates=_cand_pipeline_depth, seed=_seed_pipeline_depth,
        validate=lambda x: isinstance(x, int) and x >= 1,
    ),
    "approx_beta": KnobSpec(
        name="approx_beta", config_field="approx_beta",
        plan="hopset+bf", overrides={"hopset": True},
        candidates=_cand_approx_beta, seed=_seed_approx_beta,
        probe=_probe_approx, probe_batch=lambda v: min(8, v),
        validate=lambda x: isinstance(x, int) and x >= 2,
    ),
}

assert set(KNOB_SPECS) == set(TUNABLE_PARAMS)


def declared_tunables() -> list[tuple[str, str]]:
    """Every ``(plan_name, knob)`` pair declared by a registered Plan, in
    registry order — the tuner's work list is *derived* from the same
    plan registries dispatch walks, so a plan that stops declaring a knob
    silently drops out of tuning."""
    from paralleljohnson_tpu.backends.jax_backend import (
        FANOUT_PLANS, SSSP_PLANS,
    )
    from paralleljohnson_tpu.incremental.repair import _repair_plans
    from paralleljohnson_tpu.solver.approx import APPROX_PLANS
    from paralleljohnson_tpu.solver.johnson import SOLVER_PLANS

    out: list[tuple[str, str]] = []
    for registry in (SOLVER_PLANS, FANOUT_PLANS, SSSP_PLANS, APPROX_PLANS,
                     _repair_plans()):
        for plan in registry:
            for knob in plan.tunables:
                if (plan.name, knob) not in out:
                    out.append((plan.name, knob))
    return out


def tunable_knobs() -> list[str]:
    """Knob names declared by at least one plan, first-declaration order."""
    out: list[str] = []
    for _plan, knob in declared_tunables():
        if knob not in out and knob in KNOB_SPECS:
            out.append(knob)
    return out


# ---------------------------------------------------------------------------
# Deterministic proposals


def propose_candidates(
    knob: str,
    *,
    num_nodes: int,
    num_edges: int,
    config: SolverConfig | None = None,
    records: Sequence[dict] | None = None,
    platform: str | None = None,
) -> list:
    """Ordered candidate list for ``knob`` in the (num_nodes, num_edges)
    bucket: the config seed first (its measured wall is the promotion
    fallback), then never-measured values, then already-measured ones —
    each group sorted.  Pure in (bucket, seed, measured-set): two callers
    see the same list."""
    spec = KNOB_SPECS[knob]
    config = config or SolverConfig()
    seed = spec.seed(config, num_nodes, num_edges)
    cands = [c for c in spec.candidates(num_nodes, num_edges, seed)
             if spec.validate is None or spec.validate(c)]
    measured: set = set()
    if records:
        platform = platform or current_platform()
        bucket = shape_bucket(int(num_nodes), int(num_edges), 1)[:2]
        for rec in records:
            if rec.get("kind") not in ("plan", "tune"):
                continue
            if rec.get("platform") != platform:
                continue
            rb = shape_bucket(int(rec.get("nodes") or 0),
                              int(rec.get("edges") or 0), 1)[:2]
            if rb != bucket:
                continue
            if rec.get("kind") == "tune":
                if rec.get("knob") == knob and rec.get("value") is not None:
                    measured.add(rec["value"])
            else:
                params = rec.get("params") or {}
                if knob in params and params[knob] is not None:
                    measured.add(params[knob])
    untried = [c for c in cands if c != seed and c not in measured]
    tried = [c for c in cands if c != seed and c in measured]
    ordered = untried + tried
    if seed in cands or (spec.validate is None or spec.validate(seed)):
        ordered = [seed] + ordered
    return ordered


# ---------------------------------------------------------------------------
# Budgeted probes


@dataclasses.dataclass
class ProbeResult:
    knob: str
    value: Any
    wall_s: float | None
    censored: bool
    reason: str | None = None
    records_landed: int = 0

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def run_probe(
    graph,
    *,
    knob: str,
    value: Any,
    store: ProfileStore,
    budget_s: float,
    config: SolverConfig | None = None,
    rung: int = 0,
    label: str = "tuner",
    solve_fn: Callable[[Any, np.ndarray, SolverConfig], None] | None = None,
) -> ProbeResult:
    """One budgeted probe: solve ``graph`` with ``knob`` pinned to
    ``value`` (route forced via the knob's overrides) into a throwaway
    profile store, under a hard ``budget_s`` wall-clock cap.

    On success the probe's own ``kind:"plan"``/``kind:"solve"`` records
    are copied into ``store`` (ordinary calibration evidence — exactly
    what a forced bench run would have landed) plus one ``kind:"tune"``
    record.  A probe that exceeds the cap, or raises, lands *only* a
    ``censored: true`` tune record: its measurements are discarded, so a
    censored value can never be promoted."""
    spec = KNOB_SPECS[knob]
    if spec.validate is not None and not spec.validate(value):
        raise ValueError(f"invalid candidate for {knob}: {value!r}")
    config = config or SolverConfig()
    v = int(graph.num_nodes)
    e = int(graph.num_real_edges)
    batch = max(1, min(v, int(spec.probe_batch(v))))
    sources = np.arange(batch, dtype=np.int64)
    tmp = tempfile.mkdtemp(prefix="pj-probe-")
    probe_cfg = dataclasses.replace(
        config,
        **{spec.config_field: value},
        **spec.overrides,
        profile_store=tmp,
        checkpoint_dir=None,
    )
    fn = solve_fn or spec.probe
    box: dict[str, Any] = {}

    def _run() -> None:
        t0 = time.perf_counter()
        try:
            fn(graph, sources, probe_cfg)
            box["wall"] = time.perf_counter() - t0
        except BaseException as exc:  # noqa: BLE001 — probe sandbox
            box["error"] = f"{type(exc).__name__}: {exc}"

    worker = threading.Thread(
        target=_run, daemon=True, name=f"pj-probe-{knob}",
    )
    worker.start()
    worker.join(float(budget_s))
    platform = current_platform()
    common = dict(
        knob=knob, value=value, platform=platform,
        num_nodes=v, num_edges=e, batch=batch,
        plan=spec.plan, budget_s=float(budget_s), rung=rung, label=label,
    )
    try:
        if worker.is_alive():
            # Hard cap breached: abandon the daemon thread, discard its
            # (possibly half-written) records.
            store.append(_planner.tune_record(
                censored=True, reason="wall-clock budget exceeded", **common,
            ))
            return ProbeResult(knob, value, None, True,
                               "wall-clock budget exceeded")
        if "error" in box:
            store.append(_planner.tune_record(
                censored=True, reason=box["error"], **common,
            ))
            return ProbeResult(knob, value, None, True, box["error"])
        wall = float(box.get("wall", 0.0))
        if wall > float(budget_s):
            # Finished between join() timeout slices but over the cap:
            # still censored — the cap is the contract.
            store.append(_planner.tune_record(
                censored=True, wall_s=wall,
                reason="wall-clock budget exceeded", **common,
            ))
            return ProbeResult(knob, value, wall, True,
                               "wall-clock budget exceeded")
        landed = 0
        for rec in ProfileStore(tmp).records():
            store.append(rec)
            landed += 1
        store.append(_planner.tune_record(wall_s=wall, **common))
        return ProbeResult(knob, value, wall, False, None, landed)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


# ---------------------------------------------------------------------------
# Local tuning driver: coordinate descent + successive halving


def tune_bucket(
    graph,
    *,
    store_dir: str | Path,
    config: SolverConfig | None = None,
    knobs: Sequence[str] | None = None,
    candidates: dict[str, Sequence] | None = None,
    probe_budget_s: float = 30.0,
    bucket_budget_s: float = 120.0,
    max_rungs: int = 2,
    label: str = "tuner",
    solve_fn: Callable | None = None,
) -> dict:
    """Tune ``graph``'s shape bucket: coordinate descent over the
    declared knobs (each knob probed with every earlier knob pinned at
    its current winner), successive halving within a knob (all
    candidates probed at rung 0, the faster half re-probed per rung up
    to ``max_rungs``), everything under a per-probe cap
    (``probe_budget_s``) and a total cap (``bucket_budget_s``).

    ``bucket_budget_s <= 0`` returns immediately without opening the
    store: zero tuning budget leaves dispatch bitwise-identical.
    """
    if bucket_budget_s is not None and float(bucket_budget_s) <= 0:
        return {"probes": 0, "censored": 0, "knobs": {},
                "skipped": "zero tuning budget", "wall_s": 0.0}
    t_start = time.perf_counter()

    def remaining() -> float:
        if bucket_budget_s is None:
            return float("inf")
        return float(bucket_budget_s) - (time.perf_counter() - t_start)

    config = config or SolverConfig()
    store = ProfileStore(store_dir)
    platform = current_platform()
    v = int(graph.num_nodes)
    e = int(graph.num_real_edges)
    knob_list = list(knobs) if knobs is not None else tunable_knobs()
    summary: dict = {"probes": 0, "censored": 0, "knobs": {},
                     "skipped": None}
    base_cfg = config
    for knob in knob_list:
        if knob not in KNOB_SPECS:
            raise ValueError(f"unknown knob {knob!r}; known: "
                             f"{sorted(KNOB_SPECS)}")
        spec = KNOB_SPECS[knob]
        if remaining() <= 0:
            summary["skipped"] = f"bucket budget exhausted before {knob}"
            break
        if candidates and knob in candidates:
            cands = [c for c in candidates[knob]
                     if spec.validate is None or spec.validate(c)]
        else:
            cands = propose_candidates(
                knob, num_nodes=v, num_edges=e, config=base_cfg,
                records=store.records(), platform=platform,
            )
        seed_value = spec.seed(base_cfg, v, e)
        survivors = list(cands)
        walls: dict[Any, float] = {}
        rung = 0
        while survivors and rung <= max_rungs:
            rung_walls: dict[Any, float] = {}
            for cand in survivors:
                if remaining() <= 0:
                    summary["skipped"] = (
                        f"bucket budget exhausted during {knob} rung {rung}"
                    )
                    break
                per_probe = min(float(probe_budget_s), max(0.0, remaining()))
                res = run_probe(
                    graph, knob=knob, value=cand, store=store,
                    budget_s=per_probe, config=base_cfg, rung=rung,
                    label=label, solve_fn=solve_fn,
                )
                summary["probes"] += 1
                if res.censored:
                    summary["censored"] += 1
                else:
                    rung_walls[cand] = res.wall_s
                    walls[cand] = min(walls.get(cand, float("inf")),
                                      res.wall_s)
            if len(rung_walls) <= 1:
                break
            ranked = sorted(rung_walls, key=lambda c: rung_walls[c])
            survivors = ranked[: max(1, math.ceil(len(ranked) / 2))]
            rung += 1
        winner = tuned_value(
            knob, store_dir=str(store_dir), platform=platform,
            num_nodes=v, num_edges=e, fallback=seed_value,
        )
        summary["knobs"][knob] = {
            "seed": seed_value,
            "candidates": cands,
            "measured": {repr(k): w for k, w in sorted(
                walls.items(), key=lambda kv: kv[1])},
            "winner": winner,
            "promoted": winner is not None and winner != seed_value,
        }
        # Coordinate descent: later knobs are probed with this knob held
        # at its promoted value (or the seed when nothing beat the band).
        pinned = winner if winner is not None else seed_value
        if spec.validate is None or spec.validate(pinned):
            base_cfg = dataclasses.replace(
                base_cfg, **{spec.config_field: pinned},
            )
    summary["wall_s"] = time.perf_counter() - t_start
    return summary


# ---------------------------------------------------------------------------
# Idle-capacity farm over the round-15 coordinator


def _chunk(values: Sequence, size: int) -> list[list]:
    size = max(1, int(size))
    return [list(values[i:i + size]) for i in range(0, len(values), size)]


def _priced_chunk_size(
    store_dir: str | Path | None,
    spec: KnobSpec,
    *,
    num_edges: int,
    batch: int,
    probe_budget_s: float,
    target_lease_s: float,
) -> int:
    """Candidates per lease, priced from the CostModel: a lease should
    cost ~``target_lease_s`` of probe time.  With no model (cold store)
    each probe is priced at its worst case — the full budget cap."""
    per_probe = float(probe_budget_s)
    if store_dir:
        try:
            model = CostModel.fit(cached_records(store_dir))
            routes = (spec.plan,) if spec.plan else ()
            preds = [
                model.predict(route, num_edges=num_edges, batch=batch,
                              platform=current_platform())
                for route in routes
            ]
            preds = [p["predicted_s"] for p in preds
                     if p and p.get("predicted_s")]
            if preds:
                per_probe = min(per_probe,
                                max(MIN_PRICED_PROBE_S, 2.0 * min(preds)))
        except Exception:
            pass
    return max(1, int(float(target_lease_s) // max(per_probe, 1e-9)))


def plan_tuning_fleet(
    directory: str | Path,
    *,
    graph_spec: str,
    graph,
    knobs: Sequence[str] | None = None,
    candidates: dict[str, Sequence] | None = None,
    config: SolverConfig | None = None,
    store_dir: str | Path | None = None,
    probe_budget_s: float = 30.0,
    target_lease_s: float | None = None,
    lease_deadline_s: float = 60.0,
):
    """Write a coordinator plan whose leases are tuning jobs: one lease =
    one (knob x candidate-chunk) probe assignment on one shape bucket.
    Chunk sizes come from :func:`_priced_chunk_size` — the cost model
    prices how many probes fit in ``target_lease_s`` (default: 4 probe
    caps).  Workers attach with :func:`run_tuning_worker` (or steal
    single leases with :func:`try_tuning_lease` when idle) and the
    driver merges results with :func:`harvest_tuning`."""
    from paralleljohnson_tpu.distributed.coordinator import Coordinator
    from paralleljohnson_tpu.utils.checkpoint import graph_digest

    config = config or SolverConfig()
    v = int(graph.num_nodes)
    e = int(graph.num_real_edges)
    platform = current_platform()
    if target_lease_s is None:
        target_lease_s = 4.0 * float(probe_budget_s)
    records = list(cached_records(store_dir)) if store_dir else []
    jobs: list[dict] = []
    for knob in (list(knobs) if knobs is not None else tunable_knobs()):
        spec = KNOB_SPECS[knob]
        if candidates and knob in candidates:
            values = [c for c in candidates[knob]
                      if spec.validate is None or spec.validate(c)]
        else:
            values = propose_candidates(
                knob, num_nodes=v, num_edges=e, config=config,
                records=records, platform=platform,
            )
        if not values:
            continue
        batch = max(1, min(v, int(spec.probe_batch(v))))
        size = _priced_chunk_size(
            store_dir, spec, num_edges=e, batch=batch,
            probe_budget_s=probe_budget_s, target_lease_s=target_lease_s,
        )
        for chunk in _chunk(values, size):
            jobs.append({"knob": knob, "values": chunk,
                         "probe_budget_s": float(probe_budget_s)})
    if not jobs:
        raise ValueError("no tuning jobs: no declared knobs or candidates")
    coord = Coordinator.create(
        directory,
        graph_spec=TUNE_SPEC_PREFIX + graph_spec,
        graph_digest=graph_digest(graph),
        num_sources=len(jobs),
        lease_sources=1,
        lease_deadline_s=lease_deadline_s,
        config={"tuning": {
            "jobs": jobs,
            "graph_spec": graph_spec,
            "num_nodes": v,
            "num_edges": e,
        }},
    )
    return coord


def _tuning_spec(coord) -> dict:
    spec = coord.spec
    gspec = spec.get("graph_spec", "")
    if not str(gspec).startswith(TUNE_SPEC_PREFIX):
        from paralleljohnson_tpu.distributed.coordinator import (
            CoordinatorError,
        )
        raise CoordinatorError(
            f"{coord.dir}: not a tuning fleet (graph_spec={gspec!r}; "
            f"expected {TUNE_SPEC_PREFIX!r} prefix)"
        )
    tuning = (spec.get("config") or {}).get("tuning")
    if not tuning or "jobs" not in tuning:
        from paralleljohnson_tpu.distributed.coordinator import (
            CoordinatorError,
        )
        raise CoordinatorError(
            f"{coord.dir}: tuning fleet spec has no jobs manifest"
        )
    return spec


# Loaded probe graphs, keyed by (spec, digest): the idle hooks poll every
# few hundred ms and must not re-parse the graph per tick.
_TUNING_GRAPH_CACHE: dict[tuple[str, str], Any] = {}


def _load_tuning_graph(spec: dict, graph=None):
    from paralleljohnson_tpu.distributed.coordinator import CoordinatorError
    from paralleljohnson_tpu.utils.checkpoint import graph_digest

    if graph is None:
        key = (str(spec["config"]["tuning"]["graph_spec"]),
               str(spec["graph_digest"]))
        graph = _TUNING_GRAPH_CACHE.get(key)
        if graph is None:
            from paralleljohnson_tpu.graphs import load_graph

            graph = load_graph(spec["config"]["tuning"]["graph_spec"])
            _TUNING_GRAPH_CACHE[key] = graph
    digest = graph_digest(graph)
    if digest != spec["graph_digest"]:
        raise CoordinatorError(
            f"graph digest mismatch: fleet planned for "
            f"{spec['graph_digest']} but probe graph hashes to {digest} — "
            "refusing to land measurements from a different graph"
        )
    return graph


def _run_tuning_lease(
    coord, lease, spec: dict, graph, worker: str,
    *,
    config: SolverConfig | None = None,
    solve_fn: Callable | None = None,
) -> dict:
    """Execute one claimed tuning lease: probe its job's candidates into
    a per-lease shard store, then commit.  The shard is only harvested
    after the commit lands (manifest idiom: results from a lease that
    was requeued to another worker are ignored)."""
    jobs = spec["config"]["tuning"]["jobs"]
    shard_root = coord.shard_dir(worker)
    shard_root.mkdir(parents=True, exist_ok=True)
    shard = ProfileStore(shard_root / f"tune-lease{lease.lease_id}")
    probes = []
    for job_idx in range(lease.start, lease.stop):
        job = jobs[job_idx]
        for value in job["values"]:
            res = run_probe(
                graph, knob=job["knob"], value=value, store=shard,
                budget_s=float(job["probe_budget_s"]), config=config,
                label=f"tuner:{worker}", solve_fn=solve_fn,
            )
            probes.append(res.as_dict())
    coord.commit(lease.lease_id, worker)
    return {"lease": lease.lease_id, "probes": probes,
            "shard": str(shard.path)}


def try_tuning_lease(
    fleet_dir: str | Path,
    worker: str,
    *,
    graph=None,
    config: SolverConfig | None = None,
    solve_fn: Callable | None = None,
) -> dict | None:
    """The idle hook: claim and run at most ONE tuning lease, then
    return (``None`` when nothing is pending or the directory is not a
    tuning fleet).  Fleet workers call this between solve leases; serve
    replicas call it from their idle loop — idle capacity becomes
    calibration throughput without a dedicated tuner process."""
    from paralleljohnson_tpu.distributed.coordinator import (
        Coordinator, CoordinatorError, StaleLeaseError,
    )

    try:
        coord = Coordinator(fleet_dir)
        spec = _tuning_spec(coord)
        graph = _load_tuning_graph(spec, graph)
    except (CoordinatorError, FileNotFoundError, KeyError, ValueError):
        return None
    lease = coord.claim(worker)
    if lease is None:
        return None
    try:
        return _run_tuning_lease(
            coord, lease, spec, graph, worker,
            config=config, solve_fn=solve_fn,
        )
    except StaleLeaseError:
        return None
    except BaseException:
        try:
            coord.release(lease.lease_id, worker, reason="probe error")
        except Exception:
            pass
        raise


def run_tuning_worker(
    fleet_dir: str | Path,
    worker: str,
    *,
    graph=None,
    config: SolverConfig | None = None,
    solve_fn: Callable | None = None,
    max_leases: int | None = None,
    poll_s: float = 0.25,
    idle_timeout_s: float = 30.0,
) -> dict:
    """Drain tuning leases until the fleet is done (or ``max_leases``):
    the dedicated-worker counterpart of :func:`try_tuning_lease`.
    Crash-safe the same way solve workers are: leases lapse at the
    coordinator deadline and requeue; ``recover_worker`` requeues our
    own stragglers at startup."""
    from paralleljohnson_tpu.distributed.coordinator import (
        Coordinator, StaleLeaseError,
    )

    coord = Coordinator(fleet_dir)
    spec = _tuning_spec(coord)
    graph = _load_tuning_graph(spec, graph)
    coord.recover_worker(worker)
    done: list[dict] = []
    stale = 0
    idle_since: float | None = None
    while True:
        if max_leases is not None and len(done) >= max_leases:
            break
        lease = coord.claim(worker)
        if lease is None:
            if coord.done():
                break
            now = time.monotonic()
            if idle_since is None:
                idle_since = now
            elif now - idle_since > idle_timeout_s:
                break
            time.sleep(poll_s)
            continue
        idle_since = None
        try:
            done.append(_run_tuning_lease(
                coord, lease, spec, graph, worker,
                config=config, solve_fn=solve_fn,
            ))
        except StaleLeaseError:
            stale += 1
        except BaseException:
            try:
                coord.release(lease.lease_id, worker, reason="probe error")
            except Exception:
                pass
            raise
    return {"worker": worker, "leases": done, "stale_commits": stale,
            "fleet_done": coord.done()}


def harvest_tuning(
    fleet_dir: str | Path,
    store_dir: str | Path,
) -> dict:
    """Merge every *committed* lease's shard store into the real profile
    store, exactly once (a ``harvested.json`` ledger in the fleet dir
    records merged lease ids).  Uncommitted / requeued leases are
    skipped: the commit is the only thing that makes a shard real."""
    from paralleljohnson_tpu.distributed.coordinator import Coordinator

    coord = Coordinator(fleet_dir)
    _tuning_spec(coord)
    ledger_path = Path(fleet_dir) / HARVESTED_FILE
    harvested: set[int] = set()
    if ledger_path.exists():
        harvested = set(json.loads(ledger_path.read_text(encoding="utf-8")))
    store = ProfileStore(store_dir)
    merged = 0
    records = 0
    for lease in coord.leases():
        if lease.state != "committed" or lease.lease_id in harvested:
            continue
        shard_dir = (coord.shard_dir(lease.committed_by)
                     / f"tune-lease{lease.lease_id}")
        for rec in ProfileStore(shard_dir).records():
            store.append(rec)
            records += 1
        harvested.add(lease.lease_id)
        merged += 1
    tmp = ledger_path.with_suffix(".tmp")
    tmp.write_text(json.dumps(sorted(harvested)), encoding="utf-8")
    tmp.replace(ledger_path)
    return {"leases_harvested": merged, "records": records,
            "total_harvested": len(harvested),
            "fleet_done": coord.done()}


def provenance_table(
    *,
    store_dir: str | Path | None,
    platform: str | None = None,
    num_nodes: int,
    num_edges: int,
    config: SolverConfig | None = None,
) -> list[dict]:
    """Per-knob provenance rows for ``pjtpu info``: where each tunable's
    effective value comes from (``seed`` / ``cpu-calibrated`` /
    ``tuner-promoted``) with the backing profile-record line when one
    exists."""
    config = config or SolverConfig()
    platform = platform or current_platform()
    v, e = int(num_nodes), int(num_edges)
    rows = []
    for knob in tunable_knobs():
        spec = KNOB_SPECS[knob]
        seed = spec.seed(config, v, e)
        prov = param_provenance(
            knob, store_dir=str(store_dir) if store_dir else None,
            platform=platform, num_nodes=v, num_edges=e, fallback=seed,
        )
        rows.append({"knob": knob, "plan": spec.plan, "seed": seed, **prov})
    return rows
