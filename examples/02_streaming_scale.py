"""Streaming APSP at scales where the matrix cannot be materialized.

An RMAT-22 distance matrix is ~70 TB — rows must be reduced on device,
never stored. solve_reduced() calls your reducer once per source batch
with rows still resident on the backend's device.

Run: python examples/02_streaming_scale.py [scale]
"""

import sys

import numpy as np

import paralleljohnson_tpu as pj

scale = int(sys.argv[1]) if len(sys.argv) > 1 else 14
g = pj.load_graph(f"rmat:scale={scale},efactor=16,seed=42")
print(f"rmat-{scale}: {g.num_nodes} nodes, {g.num_real_edges} edges")

solver = pj.ParallelJohnsonSolver(pj.SolverConfig(backend="jax"))
sources = np.random.default_rng(0).choice(g.num_nodes, 64, replace=False)

# Built-in reducers: "checksum", "eccentricity", "reach_count" — or any
# callable (rows, batch_sources) -> value. Write it with jax.numpy and it
# runs on-chip; only the result crosses to the host.
red = solver.solve_reduced(g, sources=sources, reduce_rows="eccentricity")
ecc = np.concatenate(red.values)
print(f"eccentricity over {len(sources)} sources: "
      f"min={ecc.min():.2f} median={np.median(ecc):.2f} max={ecc.max():.2f}")

# A custom on-device reducer: count (source, other) pairs within
# distance 3 — unreachable entries are already +inf, and each row's own
# source (distance 0) is excluded.
import jax.numpy as jnp

def close_pairs(rows, batch):
    within = jnp.sum(rows <= 3.0)
    return int(within) - rows.shape[0]

red = solver.solve_reduced(g, sources=sources, reduce_rows=close_pairs)
print(f"pairs within distance 3: {sum(red.values):,}")
