"""Source-parallel APSP over a device mesh.

The fan-out's parallel dimension is sources: CSR is replicated per chip,
source batches shard over a 1-D Mesh, and one tiled ICI all_gather
assembles the rows. The same code runs on a real TPU pod slice and on a
simulated CPU mesh — this example forces the simulation so it runs
anywhere:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/03_multichip_mesh.py
"""

import os

if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax
import numpy as np

import paralleljohnson_tpu as pj
from paralleljohnson_tpu.utils.platform import honor_cpu_platform_request

honor_cpu_platform_request()

print("devices:", jax.devices())

g = pj.load_graph("rmat:scale=12,efactor=16,seed=1")
cfg = pj.SolverConfig(backend="jax", mesh_shape=(len(jax.devices()),))
solver = pj.ParallelJohnsonSolver(cfg)

res = solver.multi_source(g, np.arange(256))
print(f"sharded fan-out: dist {np.asarray(res.dist).shape}, "
      f"{res.stats.edges_relaxed:,} edges relaxed across the mesh")
