"""High-diameter road graphs: how the B=1 kernel routes trade off.

Road networks are the hard case for sweep-based SSSP (diameter-bound
round counts — SURVEY.md §7 "Hard parts" #1). This example runs the same
negative-weight road-grid SSSP through each route and prints the route
tag, round count, and exact candidate work, so you can see what `auto`
is choosing between:

  dia       gather-free stencil sweeps — lattice/banded labelings only,
            the TPU auto-pick when the labeling qualifies
  bucket    bucketed delta-stepping — the TPU auto-pick for B=1 solves
            when the labeling is NOT diagonal (every real road file):
            each vertex settles ~once, so candidate work collapses
  gs        blocked Gauss-Seidel — rounds ~ path direction changes,
            the TPU auto-pick for the low-degree fan-out
  frontier  compacted active-vertex relaxation — the CPU auto-pick
  sweep     full Jacobi relaxation — the baseline everything beats

The bucket row runs on a SCRAMBLED copy of the grid (where dia
declines), which is also why its distances are compared through the
label permutation rather than directly.

Run: python examples/04_road_graphs.py
(PJ_EXAMPLE_ROWS scales the grid; CI runs it tiny.)
"""

import os
import time

import numpy as np

import paralleljohnson_tpu as pj
from paralleljohnson_tpu.backends import get_backend

rows = int(os.environ.get("PJ_EXAMPLE_ROWS", "60"))
g = pj.load_graph(f"grid:rows={rows},cols={rows},neg=0.2,seed=7")
print(f"road grid: {g.num_nodes} nodes, {g.num_real_edges} edges, "
      f"diameter ~{2 * rows}")

# The honest road-file proxy: the same grid under a random labeling
# (graphs.permute_labels seed below must match the perm rebuilt here).
from paralleljohnson_tpu.graphs import permute_labels

perm = np.random.default_rng(11).permutation(g.num_nodes)
g_scrambled = permute_labels(g, seed=11)

ref = None
for tag, cfg in [
    ("dia", dict(dia=True)),
    ("bucket", dict(bucket=True)),
    ("gs", dict(dia=False, gauss_seidel=True, frontier=False)),
    ("frontier", dict(dia=False, gauss_seidel=False, frontier=True)),
    ("sweep", dict(dia=False, gauss_seidel=False, frontier=False,
                   edge_shard=False)),
]:
    be = get_backend("jax", pj.SolverConfig(**cfg))
    scrambled = tag == "bucket"
    dg = be.upload(g_scrambled if scrambled else g)
    source = int(perm[0]) if scrambled else 0
    res = be.bellman_ford(dg, source=source)  # compile + warm
    t0 = time.perf_counter()
    res = be.bellman_ford(dg, source=source)
    dt = time.perf_counter() - t0
    d = np.asarray(res.dist)
    if scrambled:
        d = d[perm]  # back to natural labels for the comparison
    ref = d if ref is None else ref
    agree = bool(np.allclose(d, ref, rtol=1e-4, atol=1e-3))
    print(f"  {tag:9s} route={res.route:9s} rounds={res.iterations:5d} "
          f"candidates={res.edges_relaxed:>13,} {dt * 1e3:8.1f} ms "
          f"agree={agree}"
          + ("  (scrambled labels — dia declines here)" if scrambled else ""))

# The same routes serve Johnson's phase 1 (virtual-source potentials) —
# `auto` picks per platform: dia/gs on TPU, frontier on CPU.
res = pj.ParallelJohnsonSolver(pj.SolverConfig()).solve(
    g, sources=np.arange(4)
)
print(f"full Johnson: phase routes {res.stats.routes_by_phase}")
