"""High-diameter road graphs: how the B=1 kernel routes trade off.

Road networks are the hard case for sweep-based SSSP (diameter-bound
round counts — SURVEY.md §7 "Hard parts" #1). This example runs the same
negative-weight road-grid SSSP through each route and prints the route
tag, round count, and exact candidate work, so you can see what `auto`
is choosing between:

  dia       gather-free stencil sweeps — lattice/banded labelings only,
            the TPU auto-pick when the labeling qualifies
  gs        blocked Gauss-Seidel — rounds ~ path direction changes,
            the TPU auto-pick for other low-degree graphs
  frontier  compacted active-vertex relaxation — the CPU auto-pick
  sweep     full Jacobi relaxation — the baseline everything beats

Run: python examples/04_road_graphs.py
(PJ_EXAMPLE_ROWS scales the grid; CI runs it tiny.)
"""

import os
import time

import numpy as np

import paralleljohnson_tpu as pj
from paralleljohnson_tpu.backends import get_backend

rows = int(os.environ.get("PJ_EXAMPLE_ROWS", "60"))
g = pj.load_graph(f"grid:rows={rows},cols={rows},neg=0.2,seed=7")
print(f"road grid: {g.num_nodes} nodes, {g.num_real_edges} edges, "
      f"diameter ~{2 * rows}")

ref = None
for tag, cfg in [
    ("dia", dict(dia=True)),
    ("gs", dict(dia=False, gauss_seidel=True, frontier=False)),
    ("frontier", dict(dia=False, gauss_seidel=False, frontier=True)),
    ("sweep", dict(dia=False, gauss_seidel=False, frontier=False,
                   edge_shard=False)),
]:
    be = get_backend("jax", pj.SolverConfig(**cfg))
    dg = be.upload(g)
    res = be.bellman_ford(dg, source=0)  # compile + warm
    t0 = time.perf_counter()
    res = be.bellman_ford(dg, source=0)
    dt = time.perf_counter() - t0
    d = np.asarray(res.dist)
    ref = d if ref is None else ref
    agree = bool(np.allclose(d, ref, rtol=1e-4, atol=1e-3))
    print(f"  {tag:9s} route={res.route:9s} rounds={res.iterations:5d} "
          f"candidates={res.edges_relaxed:>13,} {dt * 1e3:8.1f} ms "
          f"agree={agree}")

# The same routes serve Johnson's phase 1 (virtual-source potentials) —
# `auto` picks per platform: dia/gs on TPU, frontier on CPU.
res = pj.ParallelJohnsonSolver(pj.SolverConfig()).solve(
    g, sources=np.arange(4)
)
print(f"full Johnson: phase routes {res.stats.routes_by_phase}")
