"""Johnson APSP end to end: load, solve, inspect, reconstruct paths.

Run: python examples/01_apsp_basics.py
(CPU or TPU — the backend follows the visible JAX platform.)
"""

import os

import numpy as np

import paralleljohnson_tpu as pj

# Any loader spec works here: a DIMACS .gr / SNAP .txt path, or a
# generator spec (er:, dag:, rmat:, grid:). PJ_EXAMPLE_N scales the demo
# (CI runs it tiny).
n = int(os.environ.get("PJ_EXAMPLE_N", "500"))
g = pj.load_graph(f"dag:n={n},p=0.02,neg=0.35,seed=7")
print(f"graph: {g.num_nodes} nodes, {g.num_real_edges} edges, "
      f"negative weights: {g.has_negative_weights}")

solver = pj.ParallelJohnsonSolver(pj.SolverConfig(backend="jax"))

# Full APSP with shortest-path trees. dist stays on the device for device
# backends; np.asarray materializes a host copy on demand.
res = solver.solve(g, predecessors=True)
dist = np.asarray(res.dist)
finite = np.isfinite(dist)
print(f"APSP: {dist.shape}, {finite.mean():.1%} of pairs reachable")

# Reconstruct one concrete shortest path.
src = 0
reachable = np.flatnonzero(finite[src] & (np.arange(g.num_nodes) != src))
if reachable.size:
    dst = int(reachable[np.argmax(dist[src][reachable])])
    print(f"farthest vertex from {src}: {dst} at distance {dist[src, dst]:.3f}")
    print("path:", res.path(src, dst))

# Per-phase instrumentation (the attested edges-relaxed counters).
for phase, secs in res.stats.phase_seconds.items():
    print(f"  {phase:>12s}: {secs * 1e3:8.2f} ms")
print(f"  edges relaxed: {res.stats.edges_relaxed:,}")
