#!/usr/bin/env python
"""Driver benchmark: prints ONE JSON line
``{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}``.

Headline config (BASELINE.json:2 metric "edges-relaxed/sec/chip"): the
batched N-source fan-out — Johnson phase 2, the dominant hot loop
(SURVEY.md §3.1) — on an R-MAT power-law graph, run on the real TPU via
the JaxBackend. ``vs_baseline`` is the wall-clock speedup over the
scipy heap-Dijkstra path on the same graph + sources (the CPU reference
stand-in; the reference publishes no numbers, BASELINE.json:13).

Env knobs: PJ_BENCH_SCALE (default 16), PJ_BENCH_SOURCES (128),
PJ_BENCH_REPEATS (3), PJ_BENCH_DEVICE_TIMEOUT (seconds, default 900).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np


def _device_probe_ok(timeout_s: int) -> bool:
    """Probe accelerator initialization in a SUBPROCESS with a timeout.

    A wedged device tunnel blocks ``jax.devices()`` indefinitely (observed:
    a killed client left the remote TPU terminal busy for hours); probing
    in-process would hang the whole benchmark. On timeout/failure the
    caller falls back to CPU with an honestly-renamed metric rather than
    hanging the driver.
    """
    try:
        out = subprocess.run(
            [sys.executable, "-c",
             "import jax; jax.devices(); print('ok')"],
            timeout=timeout_s, capture_output=True, text=True,
        )
        return out.returncode == 0 and "ok" in out.stdout
    except subprocess.TimeoutExpired:
        return False


def main() -> None:
    smoke = "--smoke" in sys.argv
    scale = int(os.environ.get("PJ_BENCH_SCALE", "10" if smoke else "16"))
    n_sources = int(os.environ.get("PJ_BENCH_SOURCES", "16" if smoke else "128"))
    repeats = int(os.environ.get("PJ_BENCH_REPEATS", "1" if smoke else "3"))

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from paralleljohnson_tpu.utils.platform import honor_cpu_platform_request

    cpu_fallback = False
    if not honor_cpu_platform_request():
        probe_timeout = int(os.environ.get("PJ_BENCH_DEVICE_TIMEOUT", "900"))
        if not _device_probe_ok(probe_timeout):
            print(
                f"WARNING: device init did not complete in {probe_timeout}s; "
                "falling back to CPU (metric renamed)", file=sys.stderr,
            )
            os.environ["JAX_PLATFORMS"] = "cpu"
            import jax

            jax.config.update("jax_platforms", "cpu")
            cpu_fallback = True
    from paralleljohnson_tpu.backends import get_backend
    from paralleljohnson_tpu.config import SolverConfig
    from paralleljohnson_tpu.graphs import rmat

    g = rmat(scale, 16, seed=42)
    rng = np.random.default_rng(0)
    sources = np.sort(
        rng.choice(g.num_nodes, size=n_sources, replace=False)
    ).astype(np.int64)

    backend = get_backend("jax", SolverConfig())
    dgraph = backend.upload(g)
    res = backend.multi_source(dgraph, sources)  # compile + warm caches
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        res = backend.multi_source(dgraph, sources)
        times.append(time.perf_counter() - t0)
    dt = min(times)
    # edges_relaxed is aggregate across the mesh; the attested metric is
    # per-chip (BASELINE.json:2), so divide by the devices actually used.
    n_chips = int(backend._mesh().devices.size)
    edges_per_sec = res.edges_relaxed / dt / n_chips

    # CPU baseline: scipy heap Dijkstra (the reference's algorithmic shape)
    # on the identical graph + sources.
    import scipy.sparse as sp
    import scipy.sparse.csgraph as csgraph

    mat = sp.csr_matrix(
        (g.weights.astype(np.float64), g.indices, g.indptr),
        shape=(g.num_nodes, g.num_nodes),
    )
    t0 = time.perf_counter()
    ref = csgraph.dijkstra(mat, directed=True, indices=sources)
    t_ref = time.perf_counter() - t0

    ok = np.allclose(np.asarray(res.dist), ref, rtol=1e-3, atol=1e-2)
    if not ok:
        print("WARNING: TPU result mismatch vs scipy oracle", file=sys.stderr)

    tag = f"rmat{scale}x{n_sources}src"
    if cpu_fallback:
        tag += ",cpu-fallback"
    print(
        json.dumps(
            {
                "metric": f"edges_relaxed_per_sec_per_chip[{tag}]",
                "value": round(edges_per_sec, 1),
                "unit": "edges/s",
                "vs_baseline": round(t_ref / dt, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
