#!/usr/bin/env python
"""Driver benchmark: prints ONE JSON line
``{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}``.

Headline config (BASELINE.json:2 metric "edges-relaxed/sec/chip"): the
batched N-source fan-out — Johnson phase 2, the dominant hot loop
(SURVEY.md §3.1) — on an R-MAT power-law graph, run on the real TPU via
the JaxBackend. ``vs_baseline`` is the wall-clock speedup over the
scipy heap-Dijkstra path on the same graph + sources (the CPU reference
stand-in; the reference publishes no numbers, BASELINE.json:13).

Tunnel-fragility hardening (round-2, extended round-3): the
single-tenant remote-compile tunnel wedges on killed clients and on
huge first fusions, so the TPU attempt runs in a CHILD process that
ramps shapes gradually (tiny probe op -> scale-10 graph -> scale-13 ->
target). Each rung is a FULL timed measurement emitting its own
``RESULT`` line, so a wedge partway up the ramp still leaves the best
completed on-chip number (tagged ``tpu-rung`` with its actual scale)
instead of a CPU fallback. The parent enforces a per-stage watchdog and
a total budget, shuts the child down gracefully (SIGTERM, then wait) on
timeout, and falls back to CPU only if NO rung completed. A clean child
crash (not a timeout) with no results gets one retry — after a
watchdog kill the tunnel is likely wedged and retrying would burn the
budget for nothing.

Env knobs: PJ_BENCH_SCALE (default 16), PJ_BENCH_SOURCES (128),
PJ_BENCH_REPEATS (3), PJ_BENCH_DEVICE_TIMEOUT (total seconds, 1500),
PJ_BENCH_STAGE_TIMEOUT (per-stage seconds, 600),
PJ_BENCH_FIRST_STAGE_TIMEOUT (seconds until the first heartbeat, 180 —
a healthy tunnel answers jax.devices() in seconds, so a wedged one
should fail fast instead of eating the whole budget),
PJ_BENCH_CPU_SCALE (fallback graph scale, 13 — the CPU fallback must
finish within the driver's budget even on a 1-core container; the
metric tag records the actual scale run).
"""

from __future__ import annotations

import json
import os
import select
import subprocess
import sys
import time

import numpy as np

# Ramp rungs before the target: each is a FULL timed measurement that can
# become the published tpu-rung metric if the target wedges — rung config
# changes change published numbers, they are not mere warm-up.
RAMP_SCALES = (10, 13)


_IS_CHILD = False  # set in --device-inner mode


def _stage(msg: str) -> None:
    """Watchdog heartbeat: stdout in the child (piped to the parent),
    stderr in-process (stdout must stay ONE JSON line for the driver)."""
    print(f"STAGE {msg}", flush=True,
          file=sys.stdout if _IS_CHILD else sys.stderr)


def _run_config(
    scale: int, n_sources: int, repeats: int, *,
    dense_threshold: int | None = None, label: str = "target",
) -> dict:
    """Build the graph, run the fan-out on the current jax platform, and
    return the measured result dict. Shared by the child (TPU) — once per
    ramp rung and once for the target — and the parent's CPU fallback.

    The TARGET runs under the default config so the metric stays comparable
    across rounds and platforms; the ramp rungs pass ``dense_threshold=0``
    so they compile (and measure) the sparse fan-out kernel the target will
    use (rmat(10) has exactly 1024 nodes, which would otherwise hit the
    unrelated dense min-plus branch)."""
    from paralleljohnson_tpu.backends import get_backend
    from paralleljohnson_tpu.config import SolverConfig
    from paralleljohnson_tpu.graphs import rmat

    cfg = SolverConfig() if dense_threshold is None else SolverConfig(
        dense_threshold=dense_threshold
    )
    backend = get_backend("jax", cfg)

    g = rmat(scale, 16, seed=42)
    rng = np.random.default_rng(0)
    sources = np.sort(
        rng.choice(g.num_nodes, size=n_sources, replace=False)
    ).astype(np.int64)

    dgraph = backend.upload(g)
    res = backend.multi_source(dgraph, sources)  # compile + warm caches
    _stage(f"{label} scale={scale} compiled")
    # Time DEVICE compute: block_until_ready guarantees the [B, V] rows are
    # materialized in device memory before the clock stops (the KernelResult
    # sync on iterations/converged already forces the while_loop to finish).
    # The rows stay device-resident — the attested RMAT-22 workload cannot
    # materialize rows host-side at all (SURVEY.md §7), and this dev
    # environment's device tunnel transfers at ~13 MB/s, which would time
    # the tunnel, not the solver. Oracle validation downloads once, after
    # the timed repeats.
    import jax

    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        res = backend.multi_source(dgraph, sources)
        jax.block_until_ready(res.dist)
        times.append(time.perf_counter() - t0)
    dt = min(times)
    # edges_relaxed is aggregate across the mesh; the attested metric is
    # per-chip (BASELINE.json:2), so divide by the devices actually used.
    n_chips = int(backend._mesh().devices.size)

    # CPU baseline: scipy heap Dijkstra (the reference's algorithmic shape)
    # on the identical graph + sources.
    import scipy.sparse as sp
    import scipy.sparse.csgraph as csgraph

    mat = sp.csr_matrix(
        (g.weights.astype(np.float64), g.indices, g.indptr),
        shape=(g.num_nodes, g.num_nodes),
    )
    t0 = time.perf_counter()
    ref = csgraph.dijkstra(mat, directed=True, indices=sources)
    t_ref = time.perf_counter() - t0

    ok = np.allclose(np.asarray(res.dist), ref, rtol=1e-3, atol=1e-2)
    if not ok:
        print("WARNING: result mismatch vs scipy oracle", file=sys.stderr)

    roofline_bound = _append_profile(res, g, n_sources, dt, label)

    measured_out = {
        "edges_per_sec": res.edges_relaxed / dt / n_chips,
        "dt": dt,
        "t_ref": t_ref,
        "oracle_ok": bool(ok),
        "scale": scale,
        "n_sources": n_sources,
        "platform": jax.default_backend(),
        "route": getattr(res, "route", None),
        "repeats": repeats,
        # Rungs force the sparse kernel (dense_threshold=0); record it so
        # rung numbers aren't mistaken for default-config measurements.
        "config": "default" if dense_threshold is None else "sparse-forced",
    }
    if roofline_bound is not None:
        measured_out["roofline_bound"] = roofline_bound
    return measured_out


def _append_profile(res, g, n_sources: int, dt: float, label: str):
    """Cost-observatory record for the driver's own measurement (ISSUE 7
    acceptance: a CPU ``bench.py`` run persists a profile store under
    ``bench_artifacts/profiles/``). ``_run_config`` drives the backend
    directly (no solver), so the compiled-cost capture lives on
    ``res.cost`` — append it with the measured wall and a roofline
    classification. Returns the bound (or None) for the metric detail;
    never fatal."""
    try:
        profile_dir = os.environ.get("PJ_PROFILE_DIR")
        if not profile_dir:
            return None
        import jax

        from paralleljohnson_tpu.observe import ProfileStore, classify

        platform = jax.default_backend()
        cost = getattr(res, "cost", None) or {
            "cost_analysis_unavailable":
                "capture disabled for this route/backend"
        }
        roof = classify(
            flops=cost.get("flops"),
            bytes_accessed=cost.get("bytes_accessed"),
            compute_s=dt,
            platform=platform,
        )
        ProfileStore(profile_dir).append({
            "ts": time.time(),
            "kind": "bench",
            "label": f"bench.py-{label}",
            "route": getattr(res, "route", None),
            "platform": platform,
            "nodes": g.num_nodes,
            "edges": g.num_real_edges,
            "batch": int(n_sources),
            "measured": {"wall_s": dt, "compute_s": dt},
            "edges_relaxed": int(res.edges_relaxed),
            "cost": cost,
            "roofline": roof,
        })
        return roof.get("bound")
    except Exception as e:  # noqa: BLE001 — observability is never fatal
        print(f"WARNING: profile-store append failed: "
              f"{type(e).__name__}: {e}", file=sys.stderr)
        return None


def _record_history(measured: dict) -> None:
    """Cost-observatory hook (ISSUE 7): append this measurement to the
    bench-regression history under $PJ_PROFILE_DIR and WARN (stderr
    only — stdout stays the driver's single JSON line) when it regresses
    against the per-(bench, platform) trajectory. The history row keys
    on the measured config, not the fallback tag, so a cpu-fallback and
    a real TPU number never share a baseline. Never fatal, never
    changes the exit code — the driver metric must survive a broken
    history file."""
    try:
        profile_dir = os.environ.get("PJ_PROFILE_DIR")
        if not profile_dir or "edges_per_sec" not in measured:
            return
        from paralleljohnson_tpu.observe.regress import (
            BenchHistory,
            detect_regressions,
        )

        row = {
            "bench": (
                f"driver:rmat{measured['scale']}x"
                f"{measured['n_sources']}src"
            ),
            "backend": "jax",
            "platform": measured.get("platform", "unknown"),
            "preset": None,
            "wall_s": float(measured["dt"]),
            "detail": {
                "value": measured["edges_per_sec"],
                "route": measured.get("route"),
                "config": measured.get("config"),
            },
            "source": "bench.py",
        }
        hist = BenchHistory(profile_dir)
        # Wider band than the bench rows: the driver number runs on a
        # shared container and its own artifacts call the series noise.
        flagged = detect_regressions([row], hist.rows(), band=0.5)
        for f in flagged:
            print(
                f"WARNING: bench regression — {f['bench']} on "
                f"{f['platform']} took {f['wall_s']:.3f}s vs baseline "
                f"{f['baseline_s']:.3f}s ({f['slowdown']:.2f}x, "
                f"roofline: {f['roofline_bound']})",
                file=sys.stderr,
            )
        hist.append(row)
    except Exception as e:  # noqa: BLE001 — observability is never fatal
        print(f"WARNING: bench history append failed: "
              f"{type(e).__name__}: {e}", file=sys.stderr)


def _emit(measured: dict, tag: str) -> None:
    """ONE JSON line for the driver. ``detail`` carries platform + scale so
    the metric series stays interpretable across platform flips (a CPU
    fallback and an on-chip rung are distinguishable without reading
    stderr)."""
    out = {
        "metric": f"edges_relaxed_per_sec_per_chip[{tag}]",
        "value": round(measured["edges_per_sec"], 1),
        "unit": "edges/s",
        "vs_baseline": round(measured["t_ref"] / measured["dt"], 3),
    }
    detail = {
        k: measured[k]
        for k in ("platform", "scale", "n_sources", "dt", "t_ref",
                  "oracle_ok", "route", "repeats", "config",
                  "roofline_bound")
        if k in measured and measured[k] is not None
    }
    if measured.get("platform") != "tpu":
        # Round-4 verdict weak #3: the cpu-fallback series (634 -> 742
        # -> 809 M edges/s across rounds 2-4 at the same config) is
        # container-CPU noise on an unchanged kernel, not progress —
        # say so IN the artifact so a rising number can't be misread.
        detail["fallback_note"] = (
            "cpu-fallback: not a TPU measurement; round-over-round "
            "variation at this config is host noise, not kernel change"
        )
    if detail:
        out["detail"] = detail
    print(json.dumps(out))
    _record_history(measured)


def _child_main(scale: int, n_sources: int, repeats: int) -> None:
    """TPU attempt, run in a child process on the default (axon) platform.

    Every ramp rung is a FULL timed measurement that emits its own RESULT
    line (tagged with its scale), not just a warm-up: if the tunnel wedges
    partway up the ramp, the parent still holds the best completed on-chip
    measurement instead of falling back to CPU. The rungs double as the
    gradual fusion-size ramp (a huge first XLA program is a known
    tunnel-wedge trigger on this device lease)."""
    import jax

    dev = jax.devices()[0]
    # Guard the metric series: if the TPU plugin silently failed to load,
    # jax falls back to CPU devices and every RESULT would be published
    # under a tag claiming TPU. Crash instead (positive exit code = clean
    # failure; the parent falls back to CPU with an honest tag). Not an
    # assert: those vanish under PYTHONOPTIMIZE.
    if dev.platform == "cpu":
        raise SystemExit("child expected a TPU, got CPU devices")
    _stage(f"devices ok: {dev.platform}")
    # Trivial op first: confirms the compile path works before any big fusion.
    if int(jax.jit(lambda x: x + 1)(np.int32(1))) != 2:
        raise SystemExit("probe op returned a wrong value")
    _stage("probe op ok")
    for s in RAMP_SCALES:
        if s >= scale:
            break
        rung = _run_config(
            s, min(n_sources, 2 ** s), 1, dense_threshold=0, label="rung"
        )
        print("RESULT " + json.dumps(rung), flush=True)
        _stage(f"rung scale={s} measured")
    measured = _run_config(scale, n_sources, repeats)
    measured["final"] = True
    print("RESULT " + json.dumps(measured), flush=True)


def _graceful_stop(p: subprocess.Popen) -> None:
    """SIGTERM, wait, then SIGKILL only as a last resort — a hard-killed
    client is itself a known wedge trigger for the device tunnel."""
    from paralleljohnson_tpu.utils.procs import graceful_stop

    graceful_stop(p)


def _tpu_attempt(
    scale: int, n_sources: int, repeats: int,
    total_timeout: float, stage_timeout: float,
    first_stage_timeout: float | None = None,
    _cmd: list[str] | None = None,
) -> dict | None:
    """Run the child, watching STAGE heartbeats and collecting RESULT lines
    (one per ramp rung + one final). Returns the best measurement seen —
    the final target if it completed, else the highest-scale rung — or None
    on a resultless timeout, or ``{"_clean_failure": True}`` on a clean
    crash with no results (worth one retry).
    ``first_stage_timeout`` bounds the wait for the FIRST heartbeat (device
    init — seconds when healthy, forever when the tunnel is wedged).
    ``_cmd`` overrides the child command line (watchdog tests)."""
    cmd = _cmd or [
        sys.executable, os.path.abspath(__file__), "--device-inner",
        str(scale), str(n_sources), str(repeats),
    ]
    # Persistent jax compilation cache: every remote compile through the
    # single-tenant tunnel is a wedge opportunity and 20-40 s of latency;
    # a warm cache turns retries and repeat runs into cache hits. Harmless
    # if the PJRT backend can't serialize executables (jax skips caching).
    env = dict(os.environ)
    env.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.expanduser("~/.cache/pj_jax_cache"),  # user-scoped: a
        # world-predictable /tmp path invites cache poisoning on shared
        # hosts and breaks when another user owns it
    )
    # bufsize=0 + raw os.read: select() watches the fd directly, so a
    # buffered-TextIOWrapper line can never sit invisible past a select
    # wakeup and starve the stage watchdog.
    p = subprocess.Popen(
        cmd, stdout=subprocess.PIPE, stderr=sys.stderr, bufsize=0, env=env,
    )
    fd = p.stdout.fileno()
    deadline = time.monotonic() + total_timeout
    stage_deadline = time.monotonic() + (first_stage_timeout or stage_timeout)
    results: list[dict] = []
    timed_out = False
    buf = b""
    try:
        eof = False
        while not eof:
            now = time.monotonic()
            wait = min(deadline, stage_deadline) - now
            if wait <= 0:
                timed_out = True
                which = "total" if deadline <= stage_deadline else "stage"
                print(
                    f"WARNING: TPU attempt exceeded the {which} timeout; "
                    "shutting the child down gracefully", file=sys.stderr,
                )
                break
            ready, _, _ = select.select([fd], [], [], wait)
            if not ready:
                continue
            chunk = os.read(fd, 65536)
            if chunk == b"":  # EOF: child exited (or closed stdout)
                eof = True
            buf += chunk
            while b"\n" in buf:
                raw, buf = buf.split(b"\n", 1)
                line = raw.decode(errors="replace").strip()
                if line.startswith("STAGE "):
                    stage_deadline = time.monotonic() + stage_timeout
                    print(f"[tpu] {line[6:]}", file=sys.stderr)
                elif line.startswith("RESULT "):
                    # A RESULT is progress too — reset the stage watchdog.
                    stage_deadline = time.monotonic() + stage_timeout
                    results.append(json.loads(line[7:]))
        if eof:
            p.wait(30)
    except subprocess.TimeoutExpired:
        pass
    finally:
        _graceful_stop(p)
    # Only a positive exit code is a CLEAN crash worth retrying; negative
    # means killed by _graceful_stop (e.g. EOF then teardown wedge), and
    # retrying against a just-wedged tunnel burns the budget for nothing.
    clean_crash = (
        not timed_out and p.returncode is not None and p.returncode > 0
    )
    if results:
        # Any parsed RESULT is a complete, valid on-chip measurement even
        # if the child subsequently wedged (mid-ramp or in device teardown)
        # and had to be stopped — don't discard a real TPU number. Prefer
        # the final target; else the highest-scale rung that finished.
        final = [r for r in results if r.get("final")]
        best = final[-1] if final else max(
            results, key=lambda r: r.get("scale", -1)
        )
        if clean_crash and not final:
            # Crash mid-ramp on a healthy tunnel: flag for retry (which may
            # reach the target) but keep the rung as the retry's floor.
            best = dict(best, _clean_failure=True)
        return best
    if clean_crash:
        return {"_clean_failure": True}
    return None


def _strip_retry_flag(m: dict | None) -> dict | None:
    """A usable measurement (has edges_per_sec) with the retry flag
    removed; None for no-result attempts (including bare
    ``{"_clean_failure": True}``)."""
    if m is None or "edges_per_sec" not in m:
        return None
    return {k: v for k, v in m.items() if k != "_clean_failure"}


def _pick_best(floor: dict | None, retry: dict | None) -> dict | None:
    """Merge a crashed first attempt's rung (``floor``) with the retry's
    result: a completed final target always wins; otherwise the
    higher-scale rung."""
    if retry is None:
        return floor
    if floor is None or retry.get("final"):
        return retry
    return floor if floor.get("scale", -1) > retry.get("scale", -1) else retry


def main() -> None:
    smoke = "--smoke" in sys.argv
    scale = int(os.environ.get("PJ_BENCH_SCALE", "10" if smoke else "16"))
    n_sources = int(os.environ.get("PJ_BENCH_SOURCES", "16" if smoke else "128"))
    repeats = int(os.environ.get("PJ_BENCH_REPEATS", "1" if smoke else "3"))
    total_timeout = float(os.environ.get("PJ_BENCH_DEVICE_TIMEOUT", "1500"))
    stage_timeout = float(os.environ.get("PJ_BENCH_STAGE_TIMEOUT", "600"))
    first_stage_timeout = float(
        os.environ.get("PJ_BENCH_FIRST_STAGE_TIMEOUT", "180")
    )
    cpu_scale = min(
        scale, int(os.environ.get("PJ_BENCH_CPU_SCALE", "13"))
    )

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    # Cost observatory on by default for the driver bench (ISSUE 7
    # acceptance): compiled-cost capture + per-solve profile records +
    # the bench-regression history persist under bench_artifacts/profiles
    # (the child process inherits the env var).
    os.environ.setdefault(
        "PJ_PROFILE_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "bench_artifacts", "profiles"),
    )
    from paralleljohnson_tpu.utils.platform import honor_cpu_platform_request

    tag = f"rmat{scale}x{n_sources}src"
    if honor_cpu_platform_request():
        # Explicit CPU request (CI/smoke): run in-process, no device dance.
        _emit(_run_config(scale, n_sources, repeats), tag + ",cpu")
        return

    measured = _tpu_attempt(
        scale, n_sources, repeats, total_timeout, stage_timeout,
        first_stage_timeout,
    )
    if measured is not None and measured.get("_clean_failure"):
        # A rung captured before the crash is the retry's floor: if the
        # retry does no better, emit the rung rather than nothing.
        floor = _strip_retry_flag(measured)
        print("WARNING: TPU child crashed cleanly; retrying once",
              file=sys.stderr)
        retry = _strip_retry_flag(_tpu_attempt(
            scale, n_sources, repeats, total_timeout, stage_timeout,
            first_stage_timeout,
        ))
        measured = _pick_best(floor, retry)
    if measured is not None:
        if not measured.get("final") and "scale" in measured:
            # The target wedged mid-ramp; emit the best completed on-chip
            # rung, honestly tagged with the scale that actually ran.
            tag = (
                f"rmat{measured['scale']}x{measured['n_sources']}src,tpu-rung"
            )
        _emit(measured, tag)
        return

    # CPU fallback at a CPU-feasible scale: the full scale-16 config on a
    # 1-core container would blow the driver's budget and leave NO metric
    # at all; the tag records the scale actually run, so the number stays
    # honest and comparable to nothing it isn't.
    print(
        "WARNING: TPU attempt failed; falling back to CPU "
        f"(scale {scale} -> {cpu_scale}, metric renamed)",
        file=sys.stderr,
    )
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    cpu_tag = f"rmat{cpu_scale}x{n_sources}src,cpu-fallback"
    _emit(_run_config(cpu_scale, n_sources, repeats), cpu_tag)


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--device-inner":
        _IS_CHILD = True
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        _child_main(int(sys.argv[2]), int(sys.argv[3]), int(sys.argv[4]))
    else:
        main()
